"""Loss-scaler state machine tests (parity with reference
`tests/unit/test_dynamic_loss_scale.py` semantics), both the host-side class
and the jit-side functional form — including that the two stay in lockstep.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeperspeed_tpu.runtime.fp16.loss_scaler import (DynamicLossScaler,
                                                      LossScaler,
                                                      grads_finite,
                                                      init_loss_scale_state,
                                                      update_loss_scale)


def test_static_scaler():
    scaler = LossScaler(scale=128)
    assert scaler.loss_scale == 128
    assert not scaler.has_overflow([])
    scaler.update_scale(True)
    assert scaler.loss_scale == 128


def test_dynamic_halves_on_overflow():
    scaler = DynamicLossScaler(init_scale=2 ** 8, scale_window=1000)
    scaler.update_scale(True)
    assert scaler.cur_scale == 2 ** 7
    scaler.update_scale(True)
    assert scaler.cur_scale == 2 ** 6


def test_dynamic_doubles_after_window():
    scaler = DynamicLossScaler(init_scale=2 ** 8, scale_window=10)
    for _ in range(10):
        scaler.update_scale(False)
    assert scaler.cur_scale == 2 ** 9


def test_dynamic_min_scale_floor():
    scaler = DynamicLossScaler(init_scale=4, min_scale=1, scale_window=1000)
    for _ in range(10):
        scaler.update_scale(True)
    assert scaler.cur_scale == 1


def test_hysteresis_delays_shift():
    scaler = DynamicLossScaler(init_scale=2 ** 8, delayed_shift=2,
                               scale_window=1000)
    scaler.update_scale(True)   # consumes hysteresis
    assert scaler.cur_scale == 2 ** 8
    scaler.update_scale(True)   # now shifts
    assert scaler.cur_scale == 2 ** 7


def test_has_overflow():
    scaler = DynamicLossScaler()
    assert not scaler.has_overflow([jnp.ones(4)])
    assert scaler.has_overflow([jnp.ones(4),
                                jnp.array([1.0, float("inf")])])
    assert scaler.has_overflow([jnp.array([float("nan")])])


def test_grads_finite():
    good = {"a": jnp.ones(3), "b": (jnp.zeros(2),)}
    bad = {"a": jnp.ones(3), "b": (jnp.array([jnp.nan, 0.0]),)}
    assert bool(grads_finite(good))
    assert not bool(grads_finite(bad))


@pytest.mark.parametrize("window,hysteresis", [(5, 1), (3, 2), (7, 3)])
def test_functional_matches_class(window, hysteresis):
    """The jit-side state machine must track the host-side class exactly."""
    rng = np.random.default_rng(0)
    overflows = rng.random(50) < 0.3

    scaler = DynamicLossScaler(init_scale=2 ** 16, scale_window=window,
                               delayed_shift=hysteresis)
    state = init_loss_scale_state(init_scale=2 ** 16,
                                  delayed_shift=hysteresis)

    step = jax.jit(lambda s, o: update_loss_scale(
        s, o, scale_window=window, delayed_shift=hysteresis))

    for overflow in overflows:
        scaler.update_scale(bool(overflow))
        state = step(state, bool(overflow))
        assert float(state.cur_scale) == pytest.approx(scaler.cur_scale), \
            f"diverged at iter {int(state.cur_iter)}"
        assert int(state.cur_iter) == scaler.cur_iter


def test_functional_in_jit_loop():
    """State machine must be traceable through lax.scan."""
    state = init_loss_scale_state(init_scale=2 ** 4, delayed_shift=1)
    overflows = jnp.array([True, True, False, False, False])

    def body(carry, overflow):
        return update_loss_scale(carry, overflow, scale_window=2), None

    final, _ = jax.lax.scan(body, state, overflows)
    # 2**4 → /2 → /2 = 4; then 1 clean step, then window hit doubles → 8
    assert float(final.cur_scale) == 8.0
