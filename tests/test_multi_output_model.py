"""Multi-output model training (parity with reference
`tests/unit/test_multi_output_model.py`: a model producing several outputs
and a weighted multi-loss trains through the engine).
"""

import numpy as np

import jax
import jax.numpy as jnp

import deeperspeed_tpu

import pytest

# heavy jit/training integration file: excluded from the <3-min fast lane
# (run the full suite, or -m slow, to include it)
pytestmark = pytest.mark.slow


class MultiOutputModel:
    """Two heads over a shared trunk; loss = w1*mse1 + w2*mse2."""

    def __init__(self, hidden=16, weights=(1.0, 0.5)):
        self.hidden = hidden
        self.weights = weights

    def init_params(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        h = self.hidden
        return {
            "trunk": jax.random.normal(k1, (h, h)) * 0.1,
            "head_a": jax.random.normal(k2, (h, h)) * 0.1,
            "head_b": jax.random.normal(k3, (h, h)) * 0.1,
        }

    def outputs(self, params, x):
        t = jnp.tanh(x @ params["trunk"])
        return t @ params["head_a"], t @ params["head_b"]

    def loss_fn(self, params, batch, rng=None):
        x, ya, yb = batch
        out_a, out_b = self.outputs(params, x)
        w1, w2 = self.weights
        return (w1 * jnp.mean(jnp.square(out_a - ya)) +
                w2 * jnp.mean(jnp.square(out_b - yb)))


def test_multi_output_trains():
    model = MultiOutputModel()
    engine, *_ = deeperspeed_tpu.initialize(
        model=model,
        config_params={
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        })
    rng = np.random.default_rng(0)
    batch = (rng.normal(size=(1, 8, 16)).astype(np.float32),
             rng.normal(size=(1, 8, 16)).astype(np.float32),
             rng.normal(size=(1, 8, 16)).astype(np.float32))
    losses = [float(engine.train_batch(batch=batch)) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.6


def test_multi_output_forward_backward_step_api():
    """The unfused forward/backward/step path handles tuple batches too."""
    model = MultiOutputModel()
    engine, *_ = deeperspeed_tpu.initialize(
        model=model,
        config_params={
            "train_batch_size": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        })
    rng = np.random.default_rng(0)
    batch = (rng.normal(size=(8, 16)).astype(np.float32),
             rng.normal(size=(8, 16)).astype(np.float32),
             rng.normal(size=(8, 16)).astype(np.float32))
    l0 = float(engine(batch))
    engine.backward()
    engine.step()
    for _ in range(15):
        engine(batch)
        engine.backward()
        engine.step()
    l1 = float(engine(batch))
    engine.backward()  # clear cache
    engine.step()
    assert l1 < l0
