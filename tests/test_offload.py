"""Offload-tier tests: C++ aio engine, swappers, native CPU Adam (the TPU
analogues of reference `csrc/aio/py_test` sweeps and
`tests/perf/test_cpu_adam.py` / `tests/unit/test_cpu_adam.py`)."""

import os

import numpy as np
import pytest

from deeperspeed_tpu.ops.adam.cpu_adam_native import (NativeCPUAdam,
                                                      cpu_adam_available)
from deeperspeed_tpu.ops.adam.fused_adam import FusedAdam
from deeperspeed_tpu.runtime.swap_tensor.aio_engine import AsyncIOEngine
from deeperspeed_tpu.runtime.swap_tensor.async_swapper import \
    AsyncTensorSwapper
from deeperspeed_tpu.runtime.swap_tensor.optimizer_swappers import (
    OptimizerSwapper, PipelinedOptimizerSwapper)
from deeperspeed_tpu.runtime.swap_tensor.partitioned_param_swapper import \
    AsyncPartitionedParameterSwapper

# heavy jit/training integration file: excluded from the <3-min fast lane
# (run the full suite, or -m slow, to include it)
pytestmark = [pytest.mark.slow, pytest.mark.offload]

needs_aio = pytest.mark.skipif(not AsyncIOEngine.available(),
                               reason="no C++ toolchain for aio engine")
needs_cpu_adam = pytest.mark.skipif(not cpu_adam_available(),
                                    reason="no C++ toolchain for cpu adam")


@needs_aio
def test_aio_write_read_roundtrip(tmp_path):
    engine = AsyncIOEngine(block_size=4096, thread_count=4)
    data = np.random.default_rng(0).normal(size=(1 << 16,)).astype(
        np.float32)
    path = str(tmp_path / "tensor.swp")
    engine.sync_pwrite(data, path)
    out = np.empty_like(data)
    engine.sync_pread(out, path)
    np.testing.assert_array_equal(out, data)


@needs_aio
def test_aio_async_overlap(tmp_path):
    engine = AsyncIOEngine(thread_count=4)
    tensors = [np.full((1 << 14,), i, np.float32) for i in range(8)]
    for i, t in enumerate(tensors):
        engine.aio_write(t, str(tmp_path / f"t{i}.swp"))
    engine.wait()
    outs = [np.empty((1 << 14,), np.float32) for _ in range(8)]
    for i, o in enumerate(outs):
        engine.aio_read(o, str(tmp_path / f"t{i}.swp"))
    engine.wait()
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, tensors[i])


@needs_aio
def test_async_tensor_swapper(tmp_path):
    swapper = AsyncTensorSwapper()
    tensors = [np.random.default_rng(i).normal(size=(1000,)).astype(
        np.float32) for i in range(3)]
    paths = [str(tmp_path / f"s{i}.swp") for i in range(3)]
    swapper.swap_out_tensors(tensors, paths)
    swapper.synchronize_writes()
    buffers = [np.empty((1000,), np.float32) for _ in range(3)]
    swapper.swap_in_tensors(buffers, paths)
    swapper.synchronize_reads()
    for buf, t in zip(buffers, tensors):
        np.testing.assert_array_equal(buf, t)


@needs_aio
def test_partitioned_param_swapper(tmp_path):
    swapper = AsyncPartitionedParameterSwapper(
        nvme_path=str(tmp_path), buffer_count=2, buffer_size=4096)
    p0 = np.random.default_rng(0).normal(size=(32, 32)).astype(np.float32)
    p1 = np.random.default_rng(1).normal(size=(64,)).astype(np.float32)
    swapper.swap_out(0, p0)
    swapper.swap_out(1, p1)
    swapper.synchronize_writes()

    views = swapper.swap_in([0, 1], async_op=False)
    np.testing.assert_array_equal(views[0], p0)
    np.testing.assert_array_equal(views[1], p1)
    assert swapper.available_swap_in_buffers() == 0
    swapper.release([0, 1])
    assert swapper.available_swap_in_buffers() == 2


@needs_aio
@pytest.mark.parametrize("cls", [OptimizerSwapper,
                                 PipelinedOptimizerSwapper])
def test_optimizer_swapper_step(tmp_path, cls):
    swapper = cls(str(tmp_path))
    rng = np.random.default_rng(0)
    groups = {}
    for gid in range(3):
        state = {
            "master": rng.normal(size=(512,)).astype(np.float32),
            "exp_avg": np.zeros((512,), np.float32),
            "exp_avg_sq": np.zeros((512,), np.float32),
        }
        groups[gid] = {k: v.copy() for k, v in state.items()}
        swapper.initialize_group(gid, state)

    def update(gid, state):
        state["master"] = state["master"] + 1.0
        state["exp_avg"] = state["exp_avg"] + 0.5
        return state

    swapper.step([0, 1, 2], update)
    for gid in range(3):
        loaded = swapper.load_group(gid)
        np.testing.assert_allclose(loaded["master"],
                                   groups[gid]["master"] + 1.0)
        np.testing.assert_allclose(loaded["exp_avg"], 0.5)


@needs_cpu_adam
def test_native_cpu_adam_matches_fused():
    """C++ host Adam must match the jax FusedAdam trajectory (reference
    test_cpu_adam.py compares AVX Adam vs torch.optim.Adam)."""
    rng = np.random.default_rng(0)
    n = 4096
    master0 = rng.normal(size=(n,)).astype(np.float32)

    jadam = FusedAdam(lr=0.01, weight_decay=0.01, adam_w_mode=True)
    params = {"w": master0.copy()}
    state = jadam.init_state(params)

    cadam = NativeCPUAdam(lr=0.01, weight_decay=0.01, adam_w_mode=True)
    c_master = master0.copy()
    c_m = np.zeros(n, np.float32)
    c_v = np.zeros(n, np.float32)

    for step in range(5):
        grads = {"w": rng.normal(size=(n,)).astype(np.float32)}
        params, state = jadam.update(grads, state, params)
        cadam.step_flat(c_master, grads["w"], c_m, c_v)

    np.testing.assert_allclose(c_master, np.asarray(params["w"]),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(c_m, np.asarray(state.exp_avg["w"]),
                               rtol=2e-5, atol=2e-6)


@needs_cpu_adam
def test_native_cpu_adam_bf16_shadow():
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    n = 1024
    master = rng.normal(size=(n,)).astype(np.float32)
    grads = rng.normal(size=(n,)).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    bf16 = np.empty(n, np.uint16)
    adam = NativeCPUAdam(lr=0.01)
    adam.step_flat(master, grads, m, v, bf16_out=bf16)
    shadow = bf16.view(np.uint16).astype(np.uint32) << 16
    shadow = shadow.view(np.float32) if False else \
        np.frombuffer(shadow.astype(np.uint32).tobytes(), np.float32)
    np.testing.assert_allclose(shadow, master, rtol=1e-2, atol=1e-2)
    expected = np.asarray(jnp.asarray(master).astype(jnp.bfloat16)
                          .astype(jnp.float32))
    np.testing.assert_allclose(shadow, expected, rtol=1e-6, atol=1e-6)


# --- engine integration ---------------------------------------------------

@needs_cpu_adam
def test_engine_cpu_offload_matches_device(tmp_path):
    """ZeRO-Offload (cpu) must follow the same trajectory as the on-device
    optimizer."""
    import jax
    import deeperspeed_tpu
    from tests.simple_model import SimpleModel, random_batches

    model = SimpleModel(hidden_dim=16)
    params = model.init_params(__import__("jax").random.PRNGKey(7))

    def cfg(offload):
        c = {
            "train_batch_size": 8,
            "steps_per_print": 100,
            "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
            "zero_optimization": {"stage": 2},
        }
        if offload:
            c["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
        return c

    e_dev, *_ = deeperspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg(False))
    e_off, *_ = deeperspeed_tpu.initialize(
        model=model, model_parameters=params, config_params=cfg(True))
    assert e_off.host_offload

    it1 = random_batches(12, 8, 16, seed=3)
    it2 = random_batches(12, 8, 16, seed=3)
    l_dev = [float(e_dev.train_batch(data_iter=it1)) for _ in range(5)]
    l_off = [float(e_off.train_batch(data_iter=it2)) for _ in range(5)]
    np.testing.assert_allclose(l_off, l_dev, rtol=1e-4)


@needs_aio
@needs_cpu_adam
def test_engine_nvme_offload_trains(tmp_path):
    import jax
    import deeperspeed_tpu
    from tests.simple_model import SimpleModel, random_batches

    model = SimpleModel(hidden_dim=16)
    params = model.init_params(__import__("jax").random.PRNGKey(7))
    engine, *_ = deeperspeed_tpu.initialize(
        model=model, model_parameters=params, config_params={
            "train_batch_size": 8,
            "steps_per_print": 100,
            "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
            "zero_optimization": {
                "stage": 2,
                "offload_optimizer": {"device": "nvme",
                                      "nvme_path": str(tmp_path)},
            },
        })
    fixed = next(random_batches(1, 8, 16, seed=4))
    stacked = {0: None}
    import jax as _jax
    batch = _jax.tree_util.tree_map(lambda x: x[None], fixed)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert os.listdir(tmp_path / "optimizer")


@needs_cpu_adam
def test_engine_cpu_offload_checkpoint_roundtrip(tmp_path):
    import jax
    import deeperspeed_tpu
    from tests.simple_model import SimpleModel, random_batches

    def make(seed):
        model = SimpleModel(hidden_dim=16)
        params = model.init_params(__import__("jax").random.PRNGKey(seed))
        engine, *_ = deeperspeed_tpu.initialize(
            model=model, model_parameters=params, config_params={
                "train_batch_size": 8,
                "steps_per_print": 100,
                "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
                "zero_optimization": {
                    "stage": 2,
                    "offload_optimizer": {"device": "cpu"},
                },
            })
        return engine

    e1 = make(1)
    it = random_batches(10, 8, 16, seed=5)
    for _ in range(3):
        e1.train_batch(data_iter=it)
    e1.save_checkpoint(str(tmp_path), tag="off")

    e2 = make(2)
    e2.load_checkpoint(str(tmp_path), tag="off")
    it1 = random_batches(6, 8, 16, seed=9)
    it2 = random_batches(6, 8, 16, seed=9)
    la = [float(e1.train_batch(data_iter=it1)) for _ in range(3)]
    lb = [float(e2.train_batch(data_iter=it2)) for _ in range(3)]
    np.testing.assert_allclose(la, lb, rtol=1e-5)


def test_pld_theta_reaches_loss_fn_with_offload():
    """The host-offload grads path must thread pld_theta too."""
    import jax
    import jax.numpy as jnp

    import deeperspeed_tpu

    class PldModel:
        def init_params(self, rng):
            return {"w": jnp.ones((8, 8))}

        def loss_fn(self, params, batch, rng=None, pld_theta=None):
            x, y = batch
            assert pld_theta is not None
            return jnp.mean((x @ params["w"] * pld_theta - y) ** 2)

    model = PldModel()
    engine, *_ = deeperspeed_tpu.initialize(
        model=model, model_parameters=model.init_params(
            jax.random.PRNGKey(0)),
        config_params={"train_batch_size": 8,
                       "optimizer": {"type": "Adam",
                                     "params": {"lr": 1e-2}},
                       "zero_optimization": {
                           "stage": 2,
                           "offload_optimizer": {"device": "cpu"}},
                       "progressive_layer_drop": {"enabled": True,
                                                  "theta": 0.5,
                                                  "gamma": 0.1},
                       "steps_per_print": 100})
    assert engine.host_offload and engine._pld_in_loss
    x = np.ones((1, 8, 8), np.float32)
    losses = [float(engine.train_batch(batch=(x, x))) for _ in range(3)]
    assert np.isfinite(losses).all()
