"""AlexNet-as-pipeline trains to the same loss as the DP baseline
(reference `tests/unit/test_pipe.py:30` — its flagship pipeline
correctness test, on CIFAR-shaped data)."""

import numpy as np

import jax

import deeperspeed_tpu
from deeperspeed_tpu.models.vision import AlexNet, alexnet_pipe

import pytest

# heavy jit/training integration file: excluded from the <3-min fast lane
# (run the full suite, or -m slow, to include it)
pytestmark = pytest.mark.slow

STEPS = 5
BATCH = 16


def _batches():
    # one fixed CIFAR-shaped batch repeated: memorizable, so the loss
    # must fall, and both engines see identical data
    rng = np.random.default_rng(0)
    x = rng.normal(size=(1, BATCH, 32, 32, 3)).astype(np.float32) * 0.5
    y = rng.integers(0, 10, (1, BATCH)).astype(np.int32)
    return [(x, y)] * STEPS


def _config(gas=1):
    return {"train_batch_size": BATCH,
            "gradient_accumulation_steps": gas,
            "steps_per_print": 1000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}


def test_alexnet_pipeline_matches_dp_baseline():
    baseline = AlexNet()
    engine, *_ = deeperspeed_tpu.initialize(
        model=baseline,
        model_parameters=baseline.init_params(jax.random.PRNGKey(0)),
        config_params=_config())
    base_losses = [float(engine.train_batch(batch=b)) for b in _batches()]

    pipe = alexnet_pipe(num_stages=2)
    params = pipe.init_params(jax.random.PRNGKey(0),
                              example_input=np.zeros((1, 32, 32, 3),
                                                     np.float32))
    pipe_engine, *_ = deeperspeed_tpu.initialize(
        model=pipe, model_parameters=params,
        config_params=_config(gas=2))
    pipe_losses = []
    for x, y in _batches():
        xm = x.reshape(2, BATCH // 2, 32, 32, 3)
        ym = y.reshape(2, BATCH // 2)
        pipe_losses.append(float(pipe_engine.train_batch(batch=(xm, ym))))

    assert base_losses[-1] < base_losses[0]
    np.testing.assert_allclose(pipe_losses, base_losses, rtol=5e-3,
                               atol=5e-3)


def test_alexnet_partition_balanced():
    """parameter-balanced partitioning puts the conv stack and the dense
    head on different stages (real counts only exist after init_params
    — before that PipelineModule falls back to uniform)."""
    pipe = alexnet_pipe(num_stages=2)
    params = pipe.init_params(jax.random.PRNGKey(0),
                              example_input=np.zeros((1, 32, 32, 3),
                                                     np.float32))
    assert len(pipe.parts) == 3  # boundaries for 2 stages
    boundary = pipe.parts[1]
    assert 0 < boundary < len(pipe.forward_funcs)

    def numel(layer_params):
        return sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(layer_params))

    per_layer = [numel(p) for p in params["layers"]]
    stage_params = [sum(per_layer[:boundary]), sum(per_layer[boundary:])]
    # PARAMETER-balanced, not layer-count-balanced: a uniform 5/5 layer
    # split puts ~97% of AlexNet's params on stage 0 (convs 0-4 dwarf
    # nothing — the dense head is big); balanced must do better than 75/25
    assert min(stage_params) > 0
    assert min(stage_params) / sum(stage_params) > 0.25, stage_params
