"""Profile-guided schedule planner (tentpole: unify the per-kernel
autotuners' search discipline behind one cost-model-driven planner).

Fast-lane file (NO `slow` marker): the cost model is pure arithmetic,
plans are JSON files, and the probe phase is exercised with injected
counting probes — nothing here compiles a training step. The engine-
consumption path is covered through `DeepSpeedConfig` directly (the
planner block resolves + overlays before the other blocks parse).
"""

import json
import os

import pytest

import jax
import jax.numpy as jnp

from deeperspeed_tpu.ops.autotune import Autotuner
from deeperspeed_tpu.planner import cost_model as cm
from deeperspeed_tpu.planner.plan import (Plan, cached_plan,
                                          latest_plan_fingerprint,
                                          load_plan, plan_fingerprint)
from deeperspeed_tpu.planner.search import (analytic_ladder, build_plan,
                                            candidate_config,
                                            enumerate_candidates,
                                            probes_measurable)
from deeperspeed_tpu.runtime.config import (DeepSpeedConfig,
                                            parse_planner_block)
from deeperspeed_tpu.runtime.config_utils import DeepSpeedConfigError

SHAPE = cm.ModelShape(num_layers=12, hidden_size=768, num_heads=12,
                      seq_len=1024, vocab_size=50304, batch_per_chip=48)
HW = cm.hardware_profile("TPU v5 lite")


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

def test_shape_params_estimate_and_key():
    # 125M-class geometry: embed 50304*768 + 12*12*768^2 ~= 123.6M
    assert 120e6 < SHAPE.params < 130e6
    assert SHAPE.key() == (
        f"l12-h768-a12-s1024-v50304-b48-p{SHAPE.params}")
    pinned = cm.ModelShape(num_layers=12, hidden_size=768, num_heads=12,
                           seq_len=1024, vocab_size=50304,
                           batch_per_chip=48, param_count=125_000_000)
    assert pinned.params == 125_000_000


def test_remat_costs_more_compute_quant_less():
    base = cm.Candidate()
    t0 = cm.compute_time_s(base, SHAPE, HW)
    assert t0 > 0
    t_remat = cm.compute_time_s(cm.Candidate(remat=True), SHAPE, HW)
    assert t_remat == pytest.approx(t0 * cm.REMAT_COMPUTE_FACTOR)
    t_quant = cm.compute_time_s(cm.Candidate(quant_ffn="int8"), SHAPE, HW)
    assert t_quant < t0


def test_collectives_free_at_world_one():
    for mode in ("gspmd", "explicit"):
        assert cm.collective_time_s(cm.Candidate(mode=mode), SHAPE, HW,
                                    world=1) == 0.0
    # and priced beyond it, with deeper prefetch never costing more
    cand = cm.Candidate(mode="explicit", prefetch_depth=1)
    deep = cm.Candidate(mode="explicit", prefetch_depth=4)
    t1 = cm.collective_time_s(cand, SHAPE, HW, world=8)
    t4 = cm.collective_time_s(deep, SHAPE, HW, world=8)
    assert t1 > 0
    assert t4 <= t1


def test_memory_model_remat_and_offload_shrink_residency():
    base = cm.Candidate(mode="explicit")
    m0 = cm.memory_bytes(base, SHAPE, world=8, stage=3)
    assert cm.memory_bytes(cm.Candidate(mode="explicit", remat=True),
                           SHAPE, world=8, stage=3) < m0
    assert cm.memory_bytes(cm.Candidate(mode="explicit", offload="cpu"),
                           SHAPE, world=8, stage=3) < m0
    # offload is never free in time
    assert cm.offload_time_s(cm.Candidate(offload="cpu"), SHAPE, HW,
                             world=8) > 0
    assert cm.offload_time_s(base, SHAPE, HW, world=8) == 0.0


def test_memory_feasible_analytic_none_budget_never_blocks():
    cand = cm.Candidate()
    assert cm.memory_feasible_analytic(cand, SHAPE, world=1,
                                       hbm_limit=None)
    assert not cm.memory_feasible_analytic(cand, SHAPE, world=1,
                                           hbm_limit=1)


# ---------------------------------------------------------------------------
# search: enumerate -> analytic ladder -> probe degrade
# ---------------------------------------------------------------------------

def test_enumerate_collapses_gspmd_knobs_and_gates_quant():
    cands = enumerate_candidates()
    gspmd = {c for c in cands if c.mode == "gspmd"}
    # gspmd has no prefetch/bucket/group axes: one representative per
    # (remat, offload, quant)
    assert all((c.prefetch_depth, c.bucket_mb, c.group_layers)
               == (2, 32.0, 4) for c in gspmd)
    assert len(cands) == len(set(cands))
    no_quant = enumerate_candidates(allow_quant=False)
    assert all(c.quant_ffn is None for c in no_quant)
    no_off = enumerate_candidates(allow_offload=False)
    assert all(c.offload == "none" for c in no_off)


def test_analytic_ladder_ranks_and_screens():
    rungs = analytic_ladder(SHAPE, HW, world=1, top_k=4)
    assert 1 <= len(rungs) <= 4
    steps = [s["step_s"] for _, s in rungs]
    assert steps == sorted(steps)
    # an impossible budget screens everything out -> explicit error,
    # never a silent empty ladder
    hw_tiny = dict(HW, hbm_limit=1)
    with pytest.raises(ValueError, match="memory screen"):
        analytic_ladder(SHAPE, hw_tiny, world=1)


def test_candidate_config_overlay_shape():
    cfg = candidate_config(cm.Candidate(mode="explicit", prefetch_depth=4,
                                        bucket_mb=8.0, group_layers=2,
                                        remat=True, offload="cpu",
                                        quant_ffn="int8"), stage=3)
    sched = cfg["zero_optimization"]["schedule"]
    assert sched == {"mode": "explicit", "prefetch_depth": 4,
                     "bucket_mb": 8.0, "group_layers": 2, "remat": True}
    assert cfg["activation_checkpointing"]["policy"] == "full"
    off = cfg["zero_optimization"]["offload_optimizer"]
    assert off == {"device": "cpu", "buffer_count": 5}
    assert cfg["quantization"]["ffn"]["recipe"] == "int8"
    lean = candidate_config(cm.Candidate(), stage=2)
    assert lean["zero_optimization"]["stage"] == 2
    assert "offload_optimizer" not in lean["zero_optimization"]
    assert "quantization" not in lean
    assert lean["activation_checkpointing"]["policy"] == "none"


def test_probes_measurable_degrades(monkeypatch):
    assert not probes_measurable(None, None)           # no probe at all
    assert probes_measurable(lambda c: None, True)     # explicit override
    assert not probes_measurable(lambda c: None, False)
    monkeypatch.delenv("DS_TPU_AUTOTUNE", raising=False)
    assert not probes_measurable(lambda c: None, None)  # autotune off


# ---------------------------------------------------------------------------
# plan persistence
# ---------------------------------------------------------------------------

def _mini_shape():
    return cm.ModelShape(num_layers=2, hidden_size=64, num_heads=4,
                         seq_len=128, vocab_size=512, batch_per_chip=4)


def test_plan_fingerprint_stable_and_tamper_detected(tmp_path):
    payload = {"device_kind": "cpu", "shape_key": "k",
               "config": {"zero_optimization": {"stage": 3}}}
    plan = Plan(payload)
    # re-fingerprinting the fingerprinted payload is a fixed point
    assert plan_fingerprint(plan.payload) == plan.fingerprint
    path = plan.save(path=str(tmp_path / "p.json"))
    assert load_plan(path).fingerprint == plan.fingerprint
    # hand-edited plan: recorded fingerprint no longer matches content
    with open(path) as f:
        tampered = json.load(f)
    tampered["config"]["zero_optimization"]["stage"] = 2
    with open(path, "w") as f:
        json.dump(tampered, f)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        load_plan(path)


def test_cached_plan_tolerates_torn_files(tmp_path):
    assert cached_plan("cpu", "nope", cache_dir=str(tmp_path)) is None
    torn = tmp_path / "plan-cpu-torn.json"
    torn.write_text('{"version": 1, "dev')
    assert cached_plan("cpu", "torn", cache_dir=str(tmp_path)) is None
    assert latest_plan_fingerprint(cache_dir=str(tmp_path)) is None


def test_build_plan_warm_cache_skips_probes(tmp_path):
    shape = _mini_shape()
    calls = []

    def probe(cand):
        calls.append(cand)
        return jnp.zeros(())

    kwargs = dict(device_kind="cpu", world=1, top_k=3,
                  probe=probe, measurable=True,
                  cache_dir=str(tmp_path))
    plan = build_plan(shape, tuner=Autotuner(warmup=0, iters=1),
                      **kwargs)
    assert plan.probed
    assert len(calls) >= 2          # a real ladder was raced
    assert os.path.exists(plan.cache_path(cache_dir=str(tmp_path)))
    # warm cache: a fresh tuner + the persisted plan -> ZERO probes
    calls.clear()
    again = build_plan(shape, tuner=Autotuner(warmup=0, iters=1),
                       **kwargs)
    assert calls == []
    assert again.fingerprint == plan.fingerprint
    # force=True replans (and probes again)
    build_plan(shape, tuner=Autotuner(warmup=0, iters=1), force=True,
               **kwargs)
    assert len(calls) >= 2


def test_build_plan_analytic_only_without_probe(tmp_path):
    plan = build_plan(_mini_shape(), device_kind="cpu", world=1,
                      cache_dir=str(tmp_path),
                      tuner=Autotuner(warmup=0, iters=1))
    assert not plan.probed
    assert plan.payload["chosen"] in plan.payload["analytic"]["ladder"]
    # the chosen rung is the analytic winner when nothing was measured
    ladder = plan.payload["analytic"]["ladder"]
    best = min(ladder, key=lambda k: ladder[k]["step_s"])
    assert plan.payload["chosen"] == best
    # quant recipes are opt-in: analytic-only planning must not flip
    # training numerics on its own
    assert "quantization" not in plan.config
    assert latest_plan_fingerprint(cache_dir=str(tmp_path)) == \
        plan.fingerprint


# ---------------------------------------------------------------------------
# config plumbing: the strict "planner" block + merge-under overlay
# ---------------------------------------------------------------------------

def _base_cfg():
    return {"train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}


def test_parse_planner_block_strict():
    assert parse_planner_block({}) is None
    with pytest.raises(DeepSpeedConfigError, match="bogus"):
        parse_planner_block({"planner": {"plan_file": "x", "bogus": 1}})
    with pytest.raises(DeepSpeedConfigError, match="plan_file"):
        parse_planner_block({"planner": {"enabled": True}})
    with pytest.raises(DeepSpeedConfigError):
        parse_planner_block({"planner": {"enabled": "yes",
                                         "plan_file": "x"}})
    with pytest.raises(DeepSpeedConfigError):
        parse_planner_block({"planner": []})
    parsed = parse_planner_block({"planner": {"enabled": False}})
    assert parsed["enabled"] is False


def test_missing_plan_file_raises():
    with pytest.raises(DeepSpeedConfigError, match="does not exist"):
        DeepSpeedConfig({**_base_cfg(),
                         "planner": {"plan_file": "/nonexistent/p.json"}})


def test_config_consumes_plan_user_keys_win(tmp_path):
    plan = build_plan(_mini_shape(), device_kind="cpu", world=1,
                      cache_dir=str(tmp_path), save=False,
                      tuner=Autotuner(warmup=0, iters=1))
    path = plan.save(path=str(tmp_path / "plan.json"))
    ds = DeepSpeedConfig({**_base_cfg(),
                          "planner": {"plan_file": path}})
    assert ds.planner_plan_fingerprint == plan.fingerprint
    sched = plan.config["zero_optimization"]["schedule"]
    assert ds.zero_config.schedule.mode == sched["mode"]
    assert ds.zero_config.schedule.prefetch_depth == \
        sched["prefetch_depth"]
    # an explicit user key beats the plan (merge-under, never override)
    ds2 = DeepSpeedConfig({**_base_cfg(),
                           "zero_optimization": {
                               "stage": 3,
                               "schedule": {"prefetch_depth": 7}},
                           "planner": {"plan_file": path}})
    assert ds2.planner_plan_fingerprint == plan.fingerprint
    assert ds2.zero_config.schedule.prefetch_depth == 7
    # disabled block: parsed, not applied
    ds3 = DeepSpeedConfig({**_base_cfg(),
                           "planner": {"enabled": False,
                                       "plan_file": path}})
    assert ds3.planner_plan_fingerprint is None
    assert ds3.planner_applied_keys == []


def test_plan_explicit_mode_degrades_for_hookless_model(tmp_path):
    """A plan-provided schedule is advisory: mode "explicit" for a
    model without build_explicit_zero3_loss degrades to gspmd with a
    warning at engine init; a USER-set "explicit" stays a hard error."""
    import deeperspeed_tpu
    from simple_model import SimpleModel
    plan = build_plan(_mini_shape(), device_kind="cpu", world=1,
                      cache_dir=str(tmp_path), save=False,
                      tuner=Autotuner(warmup=0, iters=1))
    assert plan.config["zero_optimization"]["schedule"]["mode"] == \
        "explicit"  # default-first tie-break at world=1
    path = plan.save(path=str(tmp_path / "plan.json"))
    model = SimpleModel(hidden_dim=8)
    params = model.init_params(jax.random.PRNGKey(0))
    n = len(jax.devices())
    engine, _, _, _ = deeperspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 2 * n,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3},
                "planner": {"plan_file": path}})
    assert engine.plan_fingerprint == plan.fingerprint
    assert engine._config.zero_config.schedule.mode == "gspmd"
    assert engine._explicit_zero3_loss is None
    with pytest.raises(DeepSpeedConfigError,
                       match="build_explicit_zero3_loss"):
        deeperspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 2 * n,
                    "optimizer": {"type": "adam",
                                  "params": {"lr": 1e-3}},
                    "zero_optimization": {
                        "stage": 3,
                        "schedule": {"mode": "explicit"}}})


def test_device_kind_mismatch_warns_or_raises(tmp_path):
    payload = dict(build_plan(_mini_shape(), device_kind="TPU v4",
                              world=1, save=False,
                              tuner=Autotuner(warmup=0, iters=1)).payload)
    path = Plan(payload).save(path=str(tmp_path / "v4.json"))
    # default: warn + apply anyway
    ds = DeepSpeedConfig({**_base_cfg(), "planner": {"plan_file": path}})
    assert ds.planner_plan_fingerprint is not None
    with pytest.raises(DeepSpeedConfigError, match="strict_device_match"):
        DeepSpeedConfig({**_base_cfg(),
                         "planner": {"plan_file": path,
                                     "strict_device_match": True}})


# ---------------------------------------------------------------------------
# ds_plan CLI
# ---------------------------------------------------------------------------

def test_ds_plan_cli_json_and_show(tmp_path, capsys):
    from deeperspeed_tpu.planner.cli import main
    rc = main(["--preset", "125m", "--cache-dir", str(tmp_path),
               "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["shape_key"].startswith("l12-h768")
    assert payload["fingerprint"]
    assert payload["config"]["zero_optimization"]["stage"] == 3
    # --show prints the newest cached plan without replanning
    rc = main(["--show", "--cache-dir", str(tmp_path), "--json"])
    assert rc == 0
    shown = json.loads(capsys.readouterr().out)
    assert shown["fingerprint"] == payload["fingerprint"]
    # human-readable mode renders the ladder
    rc = main(["--show", "--cache-dir", str(tmp_path)])
    assert rc == 0
    assert "analytic ladder" in capsys.readouterr().out
    # empty cache: --show reports, exits nonzero
    rc = main(["--show", "--cache-dir", str(tmp_path / "empty")])
    assert rc == 1


def test_ds_plan_cli_requires_shape():
    from deeperspeed_tpu.planner.cli import main
    with pytest.raises(SystemExit, match="shape"):
        main(["--layers", "2"])


def test_env_report_surfaces_plan_fingerprint(tmp_path, monkeypatch):
    from deeperspeed_tpu.env_report import env_fingerprint
    monkeypatch.setenv("DS_PLAN_CACHE", str(tmp_path))
    assert env_fingerprint()["plan_fingerprint"] is None
    plan = build_plan(_mini_shape(), device_kind="cpu", world=1,
                      cache_dir=str(tmp_path),
                      tuner=Autotuner(warmup=0, iters=1))
    assert env_fingerprint()["plan_fingerprint"] == plan.fingerprint


# ---------------------------------------------------------------------------
# memory-screen edge cases (the planner's AOT screen inputs)
# ---------------------------------------------------------------------------

class _FakeDevice:
    """Duck-typed jax device for hbm_bytes_limit paths."""

    def __init__(self, platform="tpu", kind="TPU v5 lite", stats=None,
                 raise_stats=False):
        self.platform = platform
        self.device_kind = kind
        self._stats = stats
        self._raise = raise_stats

    def memory_stats(self):
        if self._raise:
            raise RuntimeError("unsupported")
        return self._stats


def test_hbm_bytes_limit_edge_cases():
    from deeperspeed_tpu.ops.autotune import hbm_bytes_limit
    # bytes_limit present -> authoritative, beats the kind table
    dev = _FakeDevice(stats={"bytes_limit": 123})
    assert hbm_bytes_limit(dev) == 123
    # stats dict WITHOUT bytes_limit (some runtimes report only usage):
    # fall through to the per-kind table
    dev = _FakeDevice(stats={"bytes_in_use": 5})
    assert hbm_bytes_limit(dev) == 16 << 30
    # memory_stats raising entirely degrades the same way
    dev = _FakeDevice(raise_stats=True, kind="TPU v4")
    assert hbm_bytes_limit(dev) == 32 << 30
    # non-TPU platform: no budget (screening skipped), never a guess
    assert hbm_bytes_limit(_FakeDevice(platform="cpu", kind="cpu",
                                       stats={})) is None
    # unknown TPU generation: None rather than a wrong number
    assert hbm_bytes_limit(_FakeDevice(kind="TPU v99",
                                       raise_stats=True)) is None


def test_compiled_memory_stats_abstract_only():
    from deeperspeed_tpu.ops.autotune import compiled_memory_stats
    ran = []

    def f(x):
        ran.append(True)
        return jnp.sum(x * x)

    arg = jax.ShapeDtypeStruct((128, 128), jnp.float32)
    stats = compiled_memory_stats(f, (arg,))
    if stats is None:
        pytest.skip("backend provides no memory_analysis()")
    # AOT only: traced for lowering, never executed on real buffers
    assert stats["argument_bytes"] >= 128 * 128 * 4
    assert stats["peak"] >= stats["argument_bytes"]
    assert stats["peak"] == max(
        stats["argument_bytes"] + stats["output_bytes"]
        + stats["temp_bytes"] - stats["alias_bytes"], 0)


def test_memory_feasible_safety_margin_boundary():
    from deeperspeed_tpu.ops.autotune import memory_feasible

    def f(x):
        return x + 1.0

    arg = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    fits, stats = memory_feasible(f, (arg,), budget_bytes=1 << 30)
    assert fits
    if stats is None:
        pytest.skip("backend provides no memory_analysis()")
    peak = stats["peak"]
    # need == budget * safety is the last feasible point ...
    exact = int(-(-peak // 0.92))          # smallest b with b*0.92 >= peak
    assert memory_feasible(f, (arg,), budget_bytes=exact)[0]
    # ... and extra_bytes (resident optimizer state the program cannot
    # see) pushes the same program over the line
    over, _ = memory_feasible(f, (arg,), budget_bytes=exact,
                              extra_bytes=max(1, int(exact * 0.1)))
    assert not over
    # budget_bytes=None (CPU: hbm_bytes_limit is None) never blocks,
    # even with huge extra_bytes
    import deeperspeed_tpu.ops.autotune as at
    if at.hbm_bytes_limit() is None:
        ok, _ = memory_feasible(f, (arg,), extra_bytes=1 << 60)
        assert ok
