"""ZeRO stage 1/2/3 correctness (parity with reference
`tests/unit/test_zero.py`: stage training correctness incl. unbalanced
gradients, plus the TPU-native assertions — state actually lives sharded on
the mesh and every stage matches an unsharded fp32 baseline bit-for-bit in
fp32).
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deeperspeed_tpu.ops.adam.fused_adam import FusedAdam
from deeperspeed_tpu.runtime.zero import (
    FP16_DeepSpeedZeroOptimizer_Stage1, FP16_DeepSpeedZeroOptimizer_Stage2,
    FP16_DeepSpeedZeroOptimizer_Stage3)
from deeperspeed_tpu.runtime.zero.stage1 import (flat_sub_partitions,
                                                 get_group_alignment_padding,
                                                 sub_partition_sizes)

# heavy jit/training integration file: excluded from the <3-min fast lane
# (run the full suite, or -m slow, to include it)
pytestmark = pytest.mark.slow

STAGES = {1: FP16_DeepSpeedZeroOptimizer_Stage1,
          2: FP16_DeepSpeedZeroOptimizer_Stage2,
          3: FP16_DeepSpeedZeroOptimizer_Stage3}


def data_mesh():
    return Mesh(np.asarray(jax.devices()[:8]), ("data",))


def mlp_params(hidden=32):
    rng = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(rng)
    return {
        "dense": {"w": jax.random.normal(k1, (hidden, hidden),
                                         jnp.float32) * 0.1,
                  "b": jnp.zeros((hidden,), jnp.float32)},
        # Deliberately non-divisible by 8 along dim 0 (unbalanced grads,
        # reference test_zero.py:13-40).
        "head": {"w": jax.random.normal(k2, (hidden, 17),
                                        jnp.float32) * 0.1},
    }


def loss_fn(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["dense"]["w"] + params["dense"]["b"])
    out = h @ params["head"]["w"]
    return jnp.mean(jnp.square(out - y))


def batch_for(hidden=32, n=16):
    rng = np.random.default_rng(0)
    return (jnp.asarray(rng.normal(size=(n, hidden)), jnp.float32),
            jnp.asarray(rng.normal(size=(n, 17)), jnp.float32))


# ---------------------------------------------------------------------------
# sub-partition math
# ---------------------------------------------------------------------------

def test_sub_partition_sizes_cover_numel():
    sizes = sub_partition_sizes(103, world=4, sub_partition_count=2)
    assert len(sizes) == 8
    assert sum(sizes) == 103


def test_flat_sub_partitions_round_robin():
    flat = np.arange(12)
    per_rank = flat_sub_partitions(flat, world=2, sub_partition_count=2)
    assert len(per_rank) == 2
    np.testing.assert_array_equal(np.concatenate(per_rank[0]),
                                  [0, 1, 2, 6, 7, 8])
    np.testing.assert_array_equal(np.concatenate(per_rank[1]),
                                  [3, 4, 5, 9, 10, 11])


def test_alignment_padding():
    assert get_group_alignment_padding(10, world=4) == 2
    assert get_group_alignment_padding(8, world=4) == 0
    assert get_group_alignment_padding(10, world=4, alignment=2) == 6


# ---------------------------------------------------------------------------
# stage correctness
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stage", [1, 2, 3])
def test_stage_matches_unsharded_baseline(stage):
    """Sharded update == replicated update (fp32, so exact up to reduction
    order)."""
    mesh = data_mesh()
    params = mlp_params()
    batch = batch_for()

    opt = STAGES[stage](FusedAdam(lr=1e-2), mesh=mesh,
                        precision=jnp.float32,
                        param_persistence_threshold=0)
    state = opt.init_state(params)

    base_opt = FusedAdam(lr=1e-2)
    base_state = base_opt.init_state(params)
    base_params = params

    step = jax.jit(opt.step)
    for i in range(5):
        grads = jax.grad(loss_fn)(state.params, batch)
        state, info = step(state, grads)
        assert not bool(info.overflow)

        base_grads = jax.grad(loss_fn)(base_params, batch)
        base_params, base_state = base_opt.update(base_grads, base_state,
                                                  base_params)

    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(base_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_state_is_sharded_on_mesh(stage):
    mesh = data_mesh()
    opt = STAGES[stage](FusedAdam(lr=1e-2), mesh=mesh,
                        param_persistence_threshold=0)
    state = opt.init_state(mlp_params())

    def is_sharded(x):
        spec = x.sharding.spec
        return any(s is not None for s in spec)

    # masters + moments sharded from stage 1
    assert is_sharded(state.master["dense"]["w"])
    assert is_sharded(state.opt_state.exp_avg["dense"]["w"])
    # compute params sharded at rest only at stage 3
    assert is_sharded(state.params["dense"]["w"]) == (stage == 3)
    # stage-3 shard really is 1/8th per device
    if stage == 3:
        shard = state.params["dense"]["w"].addressable_shards[0]
        assert shard.data.size == state.params["dense"]["w"].size // 8


def test_stage3_unbalanced_param_not_divisible():
    """17-wide head: world=8 doesn't divide any dim evenly; GSPMD pads.
    Training must still match the baseline (reference's unbalanced-gradient
    test intent)."""
    mesh = data_mesh()
    opt = FP16_DeepSpeedZeroOptimizer_Stage3(
        FusedAdam(lr=1e-2), mesh=mesh, precision=jnp.float32,
        param_persistence_threshold=0)
    params = mlp_params()
    state = opt.init_state(params)
    batch = batch_for()
    loss0 = float(loss_fn(state.params, batch))
    step = jax.jit(opt.step)
    for _ in range(10):
        grads = jax.grad(loss_fn)(state.params, batch)
        state, _ = step(state, grads)
    assert float(loss_fn(state.params, batch)) < loss0


def test_stage3_consolidated_state_dict():
    mesh = data_mesh()
    opt = FP16_DeepSpeedZeroOptimizer_Stage3(
        FusedAdam(lr=1e-2), mesh=mesh, precision=jnp.float32,
        param_persistence_threshold=0)
    params = mlp_params()
    state = opt.init_state(params)
    sd = opt.consolidated_fp16_state_dict(state)
    np.testing.assert_allclose(sd["dense"]["w"],
                               np.asarray(params["dense"]["w"]), rtol=1e-6)


@pytest.mark.parametrize("stage", [1, 2])
def test_elastic_state_dict_roundtrip(stage):
    """state_dict written under one layout restores exactly (merge of
    rank-major sub-partitions)."""
    mesh = data_mesh()
    opt = STAGES[stage](FusedAdam(lr=1e-2), mesh=mesh,
                        precision=jnp.float32)
    params = mlp_params()
    state = opt.init_state(params)
    batch = batch_for()
    step = jax.jit(opt.step)
    for _ in range(3):
        grads = jax.grad(loss_fn)(state.params, batch)
        state, _ = step(state, grads)
    sd = opt.state_dict(state)
    assert sd["partition_count"] == 8

    fresh = opt.init_state(params)
    restored = opt.load_state_dict(fresh, sd)
    for a, b in zip(jax.tree_util.tree_leaves(restored.master),
                    jax.tree_util.tree_leaves(state.master)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_overflow_skips_and_rescales():
    mesh = data_mesh()
    opt = FP16_DeepSpeedZeroOptimizer_Stage2(
        FusedAdam(lr=1e-2), mesh=mesh, dynamic_loss_scale=True,
        dynamic_loss_args={"init_scale": 2 ** 10})
    params = mlp_params()
    state = opt.init_state(params)
    before = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(state.master)]
    bad = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.nan, jnp.float32), params)
    state, info = jax.jit(opt.step)(state, bad)
    assert bool(info.overflow)
    assert float(state.scale.cur_scale) == 2 ** 9
    for a, b in zip(before, jax.tree_util.tree_leaves(state.master)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_zero_shards_replicate_ragged_dims():
    """Params with no dim divisible by the dp world (e.g. a 10-class head
    over 8 ranks) must replicate, not crash device_put (regression)."""
    from deeperspeed_tpu.runtime.zero.partition_parameters import (
        ZeroShardingRules)

    rules = ZeroShardingRules(stage=2, mesh=data_mesh(), data_axis="data")
    spec = rules.master_spec((10,))
    # PartitionSpec(None) ≡ PartitionSpec(): fully replicated
    assert all(ax is None for ax in spec)
    spec2 = rules.master_spec((10, 16))  # dim 1 divides: shard there
    assert spec2 == jax.sharding.PartitionSpec(None, "data")
