"""Sort-based MoE dispatch engine: parity with the einsum engine on the
dense and 8-device expert-parallel paths, a2a-overlap trajectory parity,
top-2 combine-weight renormalization, and auto-group memoization.

Fast lane on purpose (acceptance: sort-vs-einsum parity runs in the
<3-min lane) — shapes are tiny and jits are shared where possible."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from deeperspeed_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeperspeed_tpu.moe import (DISPATCH_MODES, MoELayer, moe_ffn_dense,
                                 moe_ffn_expert_parallel)
from deeperspeed_tpu.moe.layer import _pick_span, _resolve_groups

H, I, E = 16, 32, 8


def _params(rng, E=E):
    return MoELayer(H, I, E).init(rng)


# --- dense parity ---------------------------------------------------------

@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.parametrize("groups", [1, 4])
def test_sort_matches_einsum_dense(top_k, groups):
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, H), jnp.float32)
    y_e, aux_e = moe_ffn_dense(params, x, top_k=top_k, groups=groups)
    y_s, aux_s = moe_ffn_dense(params, x, top_k=top_k, groups=groups,
                               dispatch="sort")
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)


def test_sort_matches_einsum_capacity_overflow():
    """All tokens forced to one expert at capacity 1: the sort engine
    must drop exactly the tokens the cumsum bookkeeping drops."""
    params = _params(jax.random.PRNGKey(0))
    params["gate"] = jnp.zeros_like(params["gate"]).at[:, 0].set(1.0)
    x = jnp.ones((16, H), jnp.float32)
    y_e, _ = moe_ffn_dense(params, x, capacity_factor=E / 16)
    y_s, _ = moe_ffn_dense(params, x, capacity_factor=E / 16,
                           dispatch="sort")
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=2e-6, atol=2e-6)
    norms = np.linalg.norm(np.asarray(y_s), axis=-1)
    assert norms[0] > 1e-3 and np.all(norms[1:] < 1e-6)


def test_sort_matches_einsum_with_jitter():
    """Both engines must draw IDENTICAL gate jitter (same per-group key
    split) so they route identically under exploration noise."""
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(3), (32, H), jnp.float32)
    kw = dict(top_k=2, groups=2, rng=jax.random.PRNGKey(7),
              jitter_eps=0.3)
    y_e, aux_e = moe_ffn_dense(params, x, **kw)
    y_s, aux_s = moe_ffn_dense(params, x, dispatch="sort", **kw)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)


def test_sort_grads_match_einsum():
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(4), (24, H), jnp.float32)

    def loss(p, dispatch):
        y, aux = moe_ffn_dense(p, x, top_k=2, dispatch=dispatch)
        return jnp.sum(y ** 2) + 0.01 * aux

    g_e = jax.grad(lambda p: loss(p, "einsum"))(params)
    g_s = jax.grad(lambda p: loss(p, "sort"))(params)
    for k in g_e:
        np.testing.assert_allclose(np.asarray(g_s[k]), np.asarray(g_e[k]),
                                   rtol=2e-5, atol=2e-5, err_msg=k)


def test_sort_interpret_kernel_path():
    """Force the Pallas kernel (interpret mode on CPU) through the full
    layer — the exact code path a TPU run takes."""
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(5), (16, H), jnp.float32)
    y_e, _ = moe_ffn_dense(params, x, top_k=2)
    y_k, _ = moe_ffn_dense(params, x, top_k=2, dispatch="sort",
                           gmm_backend="pallas")
    np.testing.assert_allclose(np.asarray(y_k), np.asarray(y_e),
                               rtol=2e-6, atol=2e-6)


def test_unknown_dispatch_raises():
    params = _params(jax.random.PRNGKey(0))
    x = jnp.ones((8, H), jnp.float32)
    with pytest.raises(ValueError, match="dispatch"):
        moe_ffn_dense(params, x, dispatch="scatter")
    with pytest.raises(ValueError, match="dispatch"):
        MoELayer(H, I, E, dispatch="scatter")
    assert DISPATCH_MODES == ("einsum", "sort")


# --- top-2 combine-weight renormalization (capacity leak fix) -------------

# Routing pattern where SECOND choices overflow while first choices
# survive: tokens 0-1 route e0→e2, tokens 2-3 route e1→e2. At capacity 2
# expert2 (second choices only) keeps tokens 0-1's and drops tokens
# 2-3's — tokens 2-3 keep their first choice but lose the second.
_LOGIT_ROWS = np.asarray([[2.0, -5.0, 1.0, -5.0],
                          [2.0, -5.0, 1.0, -5.0],
                          [-5.0, 2.0, 1.0, -5.0],
                          [-5.0, 2.0, 1.0, -5.0]], np.float32)


@pytest.mark.parametrize("dispatch", ["einsum", "sort"])
def test_renorm_kept_choices_restores_leaked_mass(dispatch):
    """A token whose second choice overflows keeps weight g1/(g1+g2) < 1
    under the legacy pair normalization — the g2 mass silently leaks.
    renorm_kept_choices renormalizes over the surviving choices, so the
    token carries full weight on its kept first choice."""
    from deeperspeed_tpu.moe.layer import _one_hot_dispatch
    logits = jnp.asarray(_LOGIT_ROWS)
    _, combine, _ = _one_hot_dispatch(logits, capacity=2, top_k=2)
    per_token = np.asarray(jnp.sum(combine, axis=(1, 2)))
    probs = np.asarray(jax.nn.softmax(_LOGIT_ROWS[2]))
    g1n = probs[1] / (probs[1] + probs[2])
    # tokens 0-1 keep both choices (sum 1); tokens 2-3 lose choice 2 and
    # LEAK its mass (sum g1n < 1)
    np.testing.assert_allclose(per_token[:2], 1.0, atol=1e-5)
    np.testing.assert_allclose(per_token[2:], g1n, atol=1e-5)

    _, combine_r, _ = _one_hot_dispatch(logits, capacity=2, top_k=2,
                                        renorm_kept_choices=True)
    per_token_r = np.asarray(jnp.sum(combine_r, axis=(1, 2)))
    np.testing.assert_allclose(per_token_r, 1.0, atol=1e-5)

    # end-to-end through both engines: gate reads token dims 0/1 so x
    # rows reproduce the logit pattern above; capacity_factor 1.0 at
    # T=4/E=4/top2 → capacity 2
    params = _params(jax.random.PRNGKey(0), E=4)
    gate = jnp.zeros_like(params["gate"])
    gate = gate.at[0].set(jnp.asarray(_LOGIT_ROWS[0]))
    gate = gate.at[1].set(jnp.asarray(_LOGIT_ROWS[2]))
    params["gate"] = gate
    x = jnp.zeros((4, H), jnp.float32)
    x = x.at[0, 0].set(1.0).at[1, 0].set(1.0)
    x = x.at[2, 1].set(1.0).at[3, 1].set(1.0)
    y_r, _ = moe_ffn_dense(params, x, top_k=2, capacity_factor=1.0,
                           renorm_kept_choices=True, dispatch=dispatch)
    y_l, _ = moe_ffn_dense(params, x, top_k=2, capacity_factor=1.0,
                           dispatch=dispatch)
    # tokens 2-3 (overflowed second choice) change; tokens 0-1 don't
    diff = np.abs(np.asarray(y_r) - np.asarray(y_l)).max(axis=-1)
    assert diff[2] > 1e-6 and diff[3] > 1e-6
    assert diff[0] < 1e-7 and diff[1] < 1e-7
    y_ref, _ = moe_ffn_dense(params, x, top_k=2, capacity_factor=1.0,
                             renorm_kept_choices=True, dispatch="einsum")
    np.testing.assert_allclose(np.asarray(y_r), np.asarray(y_ref),
                               rtol=2e-6, atol=2e-6)


def test_renorm_off_is_legacy_bitwise():
    """Default off: the einsum path must stay bit-identical to the
    legacy pair normalization."""
    from deeperspeed_tpu.moe.layer import _one_hot_dispatch
    logits = jax.random.normal(jax.random.PRNGKey(8), (16, 4), jnp.float32)
    d1, c1, a1 = _one_hot_dispatch(logits, capacity=2, top_k=2)
    d2, c2, a2 = _one_hot_dispatch(logits, capacity=2, top_k=2,
                                   renorm_kept_choices=False)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


# --- auto-group memoization ----------------------------------------------

def test_resolve_groups_memoized():
    _resolve_groups.cache_clear()
    assert _resolve_groups(0, 2500) == 2     # 2500 → group size 1250
    hits0 = _resolve_groups.cache_info().hits
    assert _resolve_groups(0, 2500) == 2
    assert _resolve_groups.cache_info().hits == hits0 + 1
    # explicit counts validate (and errors are not cached)
    with pytest.raises(ValueError):
        _resolve_groups(3, 10)
    with pytest.raises(ValueError):
        _resolve_groups(3, 10)
    assert _resolve_groups("auto", 3 * 1024) == 3


# --- span / block geometry ------------------------------------------------

def test_pick_span_bounds_padding():
    for cap in (1, 7, 64, 320, 2560, 4096):
        span, bm = _pick_span(cap)
        assert span >= cap and span % bm == 0
        # padding bounded: ≤ 12.5% (+ the 8-row floor for tiny spans)
        assert span - cap <= max(cap // 8, 7)


# --- expert-parallel parity (8-device mesh) -------------------------------

def test_sort_matches_einsum_expert_parallel(devices):
    ep = 4
    mesh = Mesh(np.asarray(devices[:ep]), ("expert",))
    layer = MoELayer(H, I, E, mesh=mesh, top_k=2, groups=2)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(9), (ep * 8, H), jnp.float32)

    def build(**kw):
        return jax.jit(shard_map(
            lambda p, x: moe_ffn_expert_parallel(
                p, x, "expert", ep, top_k=2, groups=2, **kw),
            mesh=mesh, in_specs=(layer.param_specs(), P("expert")),
            out_specs=(P("expert"), P()), check_vma=False))

    y_e, aux_e = build()(params, x)
    y_s, aux_s = build(dispatch="sort")(params, x)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)

    # and both match the per-shard dense reference
    ref = jnp.concatenate([
        moe_ffn_dense(params, x[r * 8:(r + 1) * 8], top_k=2, groups=2,
                      dispatch="sort")[0] for r in range(ep)])
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_a2a_overlap_chunking_parity(devices):
    """Chunked a2a software pipelining is a pure reordering: outputs
    identical to the unchunked exchange for every chunk count."""
    ep = 4
    mesh = Mesh(np.asarray(devices[:ep]), ("expert",))
    layer = MoELayer(H, I, E, mesh=mesh, top_k=2)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(10), (ep * 8, H),
                          jnp.float32)

    def run(chunks):
        return jax.jit(shard_map(
            lambda p, x: moe_ffn_expert_parallel(
                p, x, "expert", ep, top_k=2, dispatch="sort",
                a2a_overlap_chunks=chunks),
            mesh=mesh, in_specs=(layer.param_specs(), P("expert")),
            out_specs=(P("expert"), P()), check_vma=False))(params, x)

    y1, _ = run(1)
    y2, _ = run(2)
    # e_local = 2 → a request of 3 degrades to the largest divisor (1)
    y3, _ = run(3)
    np.testing.assert_array_equal(np.asarray(y2), np.asarray(y1))
    np.testing.assert_array_equal(np.asarray(y3), np.asarray(y1))


def test_a2a_overlap_training_trajectory_parity(devices):
    """Short training trajectory (manual SGD through the EP layer):
    chunked and unchunked runs must track each other step for step."""
    ep = 4
    mesh = Mesh(np.asarray(devices[:ep]), ("expert",))
    layer = MoELayer(H, I, E, mesh=mesh, top_k=2)
    x = jax.random.normal(jax.random.PRNGKey(11), (ep * 8, H),
                          jnp.float32)
    tgt = jax.random.normal(jax.random.PRNGKey(12), (ep * 8, H),
                            jnp.float32) * 0.1

    def trajectory(chunks, steps=3, lr=0.1):
        params = layer.init(jax.random.PRNGKey(0))
        mapped = shard_map(
            lambda p, x: moe_ffn_expert_parallel(
                p, x, "expert", ep, top_k=2, dispatch="sort",
                a2a_overlap_chunks=chunks),
            mesh=mesh, in_specs=(layer.param_specs(), P("expert")),
            out_specs=(P("expert"), P()), check_vma=False)

        @jax.jit
        def step(p):
            def loss(p):
                y, aux = mapped(p, x)
                return jnp.mean((y - tgt) ** 2) + 0.01 * aux
            val, g = jax.value_and_grad(loss)(p)
            return jax.tree_util.tree_map(
                lambda w, gw: w - lr * gw, p, g), val

        losses = []
        for _ in range(steps):
            params, val = step(params)
            losses.append(float(val))
        return losses

    base = trajectory(1)
    ovl = trajectory(2)
    np.testing.assert_allclose(ovl, base, rtol=1e-6, atol=1e-7)
    assert base[-1] < base[0]


# --- config plumb-through -------------------------------------------------

def test_gpt_neox_config_plumbs_dispatch_keys():
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig
    from deeperspeed_tpu.runtime.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "moe": {"num_experts": 4, "top_k": 2, "dispatch": "sort",
                "a2a_overlap_chunks": 2, "renorm_kept_choices": True},
    }, world_size=1)
    model = GPTNeoX(GPTNeoXConfig.tiny(), use_pallas=False)
    model.apply_ds_config(cfg)
    assert model.config.moe_dispatch == "sort"
    assert model.config.moe_a2a_overlap_chunks == 2
    assert model.config.moe_renorm_kept_choices is True
    assert model.config.moe_num_experts == 4
