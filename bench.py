"""Benchmark: GPT-NeoX training throughput on the attached TPU chip(s).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric is tokens/sec/chip for a bf16 GPT-NeoX training step (ZeRO-sharded
over whatever devices are attached). ``vs_baseline`` is MFU / 0.40 — the
BASELINE.md north-star is ≥40% MFU, so ≥1.0 means target hit.
"""

import json
import sys
import time

import numpy as np


def peak_flops_per_chip(device):
    """bf16 peak TFLOPS by TPU generation (public spec sheet numbers)."""
    kind = getattr(device, "device_kind", "") or str(device)
    kind = kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6": 918e12, "v6e": 918e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # conservative default


def main():
    import jax

    import deeperspeed_tpu
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig

    devices = jax.devices()
    n_chips = len(devices)

    # ~115M-param GPT-NeoX (GPT2-small scale), seq 1024.
    cfg = GPTNeoXConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024)
    import os
    seq = 1024
    # bs48 fits the 16GB chip with the single-block attention kernels and
    # runs ~1.5% higher MFU than bs32 (bs64 OOMs); override via env.
    batch_per_chip = int(os.environ.get("DS_BENCH_BS", "48"))
    batch = batch_per_chip * n_chips

    model = GPTNeoX(cfg, use_pallas=True)
    params = model.init_params(jax.random.PRNGKey(0))

    engine, *_ = deeperspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params={
            "train_batch_size": batch,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10_000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "fp16": {"enabled": True, "type": "bfloat16"},
            "zero_optimization": {"stage": 2},
        })

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                          dtype=np.int32)
    stacked = (tokens, tokens)

    def force(tree):
        """Materialize on host: `block_until_ready` alone is not a reliable
        fence on tunneled/remote backends — an actual transfer is."""
        jax.block_until_ready(tree)
        return np.asarray(jax.tree_util.tree_leaves(tree)[0])

    # Warmup (compile) + 2 stabilization steps.
    for _ in range(3):
        loss = engine.train_batch(batch=stacked)
    force(engine.state.params)

    n_steps = 10
    start = time.perf_counter()
    for _ in range(n_steps):
        loss = engine.train_batch(batch=stacked)
    force(engine.state.params)
    elapsed = time.perf_counter() - start

    tokens_per_sec = batch * seq * n_steps / elapsed
    tokens_per_sec_chip = tokens_per_sec / n_chips

    n_params = cfg.num_params()
    model_flops_per_token = 6 * n_params  # fwd+bwd dense transformer
    # attention flops: 12 * L * h * s per token (qk + pv, fwd+bwd)
    attn_flops_per_token = 12 * cfg.num_layers * cfg.hidden_size * seq
    flops_per_token = model_flops_per_token + attn_flops_per_token
    achieved = tokens_per_sec_chip * flops_per_token
    peak = peak_flops_per_chip(devices[0])
    mfu = achieved / peak

    # Secondary configs (BASELINE's primary metric is tokens/s/chip under
    # ZeRO-3; an offload tier shows the capacity ladder's cost). Fewer
    # steps — these report alongside, not as, the headline number.
    import gc
    final_loss = float(loss)
    del engine, loss  # bs48 leaves no HBM headroom for two live engines
    gc.collect()

    def measure_config(zero_cfg, steps=3, warmup=2):
        eng, *_ = deeperspeed_tpu.initialize(
            model=model,
            model_parameters=params,
            config_params={
                "train_batch_size": batch,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 10_000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "fp16": {"enabled": True, "type": "bfloat16"},
                "zero_optimization": zero_cfg,
            })
        for _ in range(warmup):
            eng.train_batch(batch=stacked)
        force(eng.state.params)
        t0 = time.perf_counter()
        for _ in range(steps):
            eng.train_batch(batch=stacked)
        force(eng.state.params)
        dt = time.perf_counter() - t0
        tps = batch * seq * steps / dt / n_chips
        del eng
        gc.collect()
        return round(tps, 1), round(tps * flops_per_token / peak, 4)

    extra_configs = {}
    try:
        # warmup 4 / steps 8: short windows under-measured stage 3 by
        # ~5% in round 2 (tunnel-side variance, donation retrace); at
        # equal methodology stage 3 == stage 2 on one chip (world=1
        # gathers are copies, measured ratio 1.000 at bs48)
        tps3, mfu3 = measure_config({"stage": 3}, steps=8, warmup=4)
        extra_configs["zero3_tokens_per_sec_chip"] = tps3
        extra_configs["zero3_mfu"] = mfu3
    except Exception as e:  # noqa: BLE001 - report, don't fail the bench
        extra_configs["zero3_error"] = f"{type(e).__name__}: {e}"[:200]
    # Host-offload is only measured when the chip link is local: every
    # step moves the full grad set device→host and params back, which a
    # tunneled chip turns into minutes per step (measured; a TPU-VM's
    # local PCIe link is the real deployment). Opt in via env.
    if os.environ.get("DS_BENCH_OFFLOAD", "0") not in ("0", "", "false"):
        try:
            tpso, mfuo = measure_config(
                {"stage": 2, "offload_optimizer": {"device": "cpu"}},
                steps=2, warmup=1)
            extra_configs["zero2_offload_tokens_per_sec_chip"] = tpso
            extra_configs["zero2_offload_mfu"] = mfuo
        except Exception as e:  # noqa: BLE001
            extra_configs["offload_error"] = \
                f"{type(e).__name__}: {e}"[:200]

    print(json.dumps({
        "metric": "gpt_neox_125m_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "chips": n_chips,
            "device": str(devices[0]),
            "mfu": round(mfu, 4),
            "achieved_tflops_per_chip": round(achieved / 1e12, 2),
            "params_m": round(n_params / 1e6, 1),
            "final_loss": final_loss,
            "seq": seq,
            "batch_per_chip": batch_per_chip,
            **extra_configs,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
