"""Benchmark: training throughput on the attached TPU chip(s).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric is tokens/sec/chip for a bf16 GPT-NeoX-125M training step
(ZeRO-2); ``vs_baseline`` is MFU / 0.40 — the BASELINE.md north-star is
≥40% MFU, so ≥1.0 means target hit.

``extra`` carries the config ladder. Resilience (VERDICT r4): every row
runs in its OWN subprocess — an OOM'd row cannot poison the others'
HBM (a raised RESOURCE_EXHAUSTED pins the dead engine via the exception
traceback; in round 4 one OOM cascaded into three) — and rows degrade
through a config ladder (smaller batch, more remat, offload tiers)
before reporting an error. DS_BENCH_ROWS selects a comma list of row
KEYS (default all):
  - zero3    (GPT-NeoX-125M, ZeRO-3)
  - bert128 / bert512  (BERT-Large: masked + fused in-kernel attention
             dropout — the reference's flagship single-device workload,
             docs/_tutorials/bert-pretraining.md)
  - gpt2xl   (gpt2_xl_1p5b: Megatron-GPT2 48L/1600H ladder rung, ZeRO-3
             + CPU-offload optimizer tier; reference
             tests/model/Megatron_GPT2)
  - longseq  (longseq_16k: 16k-token causal flash row)
  - moe      (moe_top2: GShard top-2 MoE row; walks the einsum and
             sort dispatch engines — DS_BENCH_MOE_DISPATCH narrows)
  - ckpt     (checkpoint-induced step stall, sync vs async
             snapshot-then-commit save; opt-in via DS_BENCH_CKPT=1 —
             disk-heavy)
  - sentinel (training-health sentinel detection overhead + injected-
             fault recovery latency; opt-in via DS_BENCH_SENTINEL=1)
  - telemetry (unified-telemetry scalars-on overhead + in-engine MFU
             vs analytic MFU cross-check; opt-in via DS_BENCH_TELEMETRY=1)
  - packed   (packed ragged-batch row: fixed-seed lognormal doc mixture
             packed into 16k rows, segment-aware kernels vs the same
             shapes without segments; opt-in via DS_BENCH_PACKED=1)
  - serve    (continuous-batching serving row: fixed-seed open-loop
             request stream through the InferenceEngine's paged KV
             cache; generated tokens/s/chip + p50/p99 per-token latency
             + zero-recompile check; opt-in via DS_BENCH_SERVE=1)
  - serve_chaos (serving-under-failure row: the serve stream run clean
             and again under a scripted fault storm — injected decode
             errors, a decode stall, page-pool pressure — against a
             bounded admission queue; success rate, shed fraction, p99
             TTFT degradation storm-vs-clean, and the chaos invariants
             (server up, zero leaked pages, zero post-warmup
             recompiles); opt-in via DS_BENCH_SERVE_CHAOS=1)
  - serve_prefix (prefix-cache + speculative-decode serving row: a
             bursty 80%-shared-prefix stream run cache-off, then with
             the prefix registry + a small draft model after a
             two-stream warmup; prefix hit rate, effective prefill
             tok/s vs cache-off, spec acceptance rate, p50 inter-token
             speedup, steady-state compile delta (must be 0); opt-in
             via DS_BENCH_SERVE_PREFIX=1)
  - serve_disagg (disaggregated prefill/decode serving row: the bursty
             80%-shared-prefix stream run unified vs a prefill-pool +
             decode-pool split over the in-memory handoff transport;
             tokens/s for both layouts, decode-side p50/p99 inter-token
             latency under the prefill bursts, handoff round-trip p50
             ms, post-warmup compile delta over both pools (must be 0);
             opt-in via DS_BENCH_SERVE_DISAGG=1)
  - elastic  (supervised-restart recovery: a hard mid-run kill under the
             elasticity supervisor — kill -> resumed-step wall clock
             (MTTR) and steps lost vs the committed checkpoint; opt-in
             via DS_BENCH_ELASTIC=1)
  - pipe     (config-driven 1F1B pipeline rows: NeoX-125M over 2/4
             stages x remaining-chips ZeRO-1 data parallel, classic and
             comm-overlap wire schedules, analytic bubble fraction +
             zero-recompile check; opt-in via DS_BENCH_PIPE=1)
  - offload  (tiered-offload rows: the explicit schedule on-chip vs
             host-DRAM rows vs NVMe rows (DS_BENCH_OFFLOAD_NVME=path)
             with step time / prefetch-stall fraction / h2d+d2h wire
             volume, plus a DS_BENCH_OFFLOAD_RATIO x-HBM synthetic rung
             trained on the host tier vs the flops-extrapolated on-chip
             time; opt-in via DS_BENCH_OFFLOAD=1)
  - quant    (low-precision rows: bf16 vs int8-weight decode tokens/s +
             p50 inter-token on a decode-heavy serve stream, int8-KV
             resident-session capacity at fixed pool bytes (scale pools
             included), compressed vs dense cross-host DP-grad step
             time on the explicit ZeRO-3 schedule; knobs in
             quant_knobs; opt-in via DS_BENCH_QUANT=1)
  - plan     (schedule-planner row: build_plan's planner-chosen config
             vs the hand-default explicit schedule on the 125M zero3
             ladder, plan fingerprint + chosen label in extra; opt-in
             via DS_BENCH_PLAN=1)
  - rl       (online-RL row: the co-located train+serve PPO loop on a
             CPU-proxy NeoX — rollout tokens/s under the
             continuous-batching scheduler, update-step ms, train->serve
             hot-swap latency, the zero-recompile pin (compile delta 0
             after warmup), and the co-residency tax: the same
             pretraining step timed alone vs with the RL pair resident
             (<=10% degradation target); opt-in via DS_BENCH_RL=1)
  - multislice (two-slice DCN drill on a CPU-drivable proxy: 1F1B split
             across a simulated slice boundary with dcn_delay charged
             per exposed crossing — classic vs comm-overlap wire
             throughput ratio vs single-slice, the overlap wire holding
             the <=10%-loss bar — plus a scripted slice_kill: detection
             -> emergency checkpoint -> in-process re-partition MTTR,
             zero survivor restarts, loss-trajectory alignment vs an
             unfaulted reference; opt-in via DS_BENCH_MULTISLICE=1)

The zero3 row additionally measures `zero3_explicit` — the explicit
shard_map collective schedule (layer-ahead bucketed all-gather prefetch,
reduce-scatter at layer-backward boundaries) vs the GSPMD path, with
prefetch depth / bucket MB / group size in extra
(DS_BENCH_ZERO3_PREFETCH / _BUCKET_MB / _GROUP).
"""

import gc
import json
import os
import resource
import signal
import subprocess
import sys
import time

import numpy as np

ROW_ORDER = ["zero3", "bert128", "bert512", "gpt2xl", "longseq", "moe"]
ROW_TIMEOUT = {"gpt2xl": 1100, "longseq": 1100, "ckpt": 600,
               "sentinel": 600, "telemetry": 600, "packed": 800,
               "moe": 800, "serve": 800, "serve_chaos": 900,
               "serve_prefix": 900, "serve_disagg": 900,
               "zero3": 800, "pipe": 900, "offload": 1100,
               "elastic": 600, "fleet": 600,
               "quant": 1100,  # moe/longseq/quant walk both engines
               "plan": 1100,  # two full 125m variants (race both ways)
               "rl": 900,
               "multislice": 900}

ROW_TIMEOUT_DEFAULT = 420


def peak_flops_per_chip(device):
    """bf16 peak TFLOPS by TPU generation — the table lives in
    `deeperspeed_tpu.profiling.hardware` (shared with the in-engine
    telemetry MFU, so bench and live scalars can never disagree)."""
    from deeperspeed_tpu.profiling.hardware import \
        peak_flops_per_chip as _peak
    return _peak(device)


def force(tree):
    """Materialize on host: `block_until_ready` alone is not a reliable
    fence on tunneled/remote backends — an actual transfer is."""
    import jax
    jax.block_until_ready(tree)
    return np.asarray(jax.tree_util.tree_leaves(tree)[0])


def timed_steps(engine, batch, steps, warmup):
    for _ in range(warmup):
        loss = engine.train_batch(batch=batch)
    force(engine.state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    force(engine.state.params)
    return time.perf_counter() - t0, float(loss)


def _setup_jax():
    import jax
    cache_dir = os.environ.get("DS_BENCH_CACHE",
                               os.path.join(os.path.dirname(
                                   os.path.abspath(__file__)), ".xla_cache"))
    if cache_dir:
        # persistent compile cache: re-runs and ladder retries skip the
        # 20-40s per-program XLA compile
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return jax


def _ladder(rungs, out, name):
    """Try configs in order until one produces numbers. Each rung is
    (tag, thunk) with thunk() -> dict of extra keys. Failures are
    recorded per-rung; the first success also records which rung ran."""
    errs = []
    for tag, thunk in rungs:
        try:
            res = thunk()
            out.update(res)
            out[f"{name}_config"] = tag
            if errs:
                out[f"{name}_degraded_from"] = "; ".join(errs)[:300]
            return out
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            errs.append(f"{tag}: {type(e).__name__}: {e}"[:160])
            # drop the traceback before gc: its frames pin the dead
            # engine (and its HBM buffers) — the round-4 cascade
            e.__traceback__ = None
            del e
            gc.collect()
    out[f"{name}_error"] = " | ".join(errs)[:400]
    return out


# ---------------------------------------------------------------------------
# rows (each runs in its own subprocess)
# ---------------------------------------------------------------------------

def _neox_engine(model, params, batch, zero_cfg, extra_cfg=None):
    import deeperspeed_tpu
    config_params = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10_000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "fp16": {"enabled": True, "type": "bfloat16"},
        "zero_optimization": zero_cfg,
    }
    if extra_cfg:
        config_params.update(extra_cfg)
    eng, *_ = deeperspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        config_params=config_params)
    return eng


def _headline_setup(jax):
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig
    cfg = GPTNeoXConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024)
    model = GPTNeoX(cfg, use_pallas=True)
    params = model.init_params(jax.random.PRNGKey(0))
    return cfg, model, params


def _flops_per_token(cfg, seq):
    return 6 * cfg.num_params() + 12 * cfg.num_layers * cfg.hidden_size * seq


def row_zero3():
    """ZeRO-3 row: the GSPMD path (XLA schedules the param gathers) AND
    the explicit shard_map schedule (zero_optimization.schedule.mode
    "explicit": bucketed all-gathers prefetched DS_BENCH_ZERO3_PREFETCH
    layers ahead, reduce-scatters at layer-backward boundaries) — the
    head-to-head that closes the BENCH_r05 zero3-vs-ddp gap. Prefetch
    depth / bucket MB / remat-group size ride in extra."""
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    cfg, model, params = _headline_setup(jax)
    seq = min(int(os.environ.get("DS_BENCH_SEQ", "1024")),
              cfg.max_seq_len)
    prefetch = int(os.environ.get("DS_BENCH_ZERO3_PREFETCH", "2"))
    bucket_mb = float(os.environ.get("DS_BENCH_ZERO3_BUCKET_MB", "32"))
    group = int(os.environ.get("DS_BENCH_ZERO3_GROUP", "4"))
    # remat off by default: the ddp/gspmd rows this one races do not
    # remat either — apples to apples (the 125M fits with the gathered
    # buffers resident; flip on for memory-bound shapes)
    remat = os.environ.get("DS_BENCH_ZERO3_REMAT", "0") not in (
        "0", "", "false")
    bs_ladder = [int(b) for b in os.environ.get(
        "DS_BENCH_ZERO3_BS", "48,32").split(",")]

    def run(bs, explicit):
        def thunk():
            batch = bs * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            zero_cfg = {"stage": 3}
            tag = "zero3"
            if explicit:
                tag = "zero3_explicit"
                zero_cfg["schedule"] = {
                    "mode": "explicit", "prefetch_depth": prefetch,
                    "bucket_mb": bucket_mb, "group_layers": group,
                    "remat": remat}
            eng = _neox_engine(model, params, batch, zero_cfg)
            steps = 12
            dt, _ = timed_steps(eng, (tokens, tokens), steps=steps,
                                warmup=4)
            tps = batch * seq * steps / dt / n_chips
            out = {f"{tag}_tokens_per_sec_chip": round(tps, 1),
                   f"{tag}_mfu": round(
                       tps * _flops_per_token(cfg, seq) / peak, 4)}
            if explicit:
                out["zero3_explicit_prefetch_depth"] = prefetch
                out["zero3_explicit_bucket_mb"] = bucket_mb
                out["zero3_explicit_group_layers"] = group
                out["zero3_explicit_remat"] = remat
            return out
        return thunk

    out = _ladder([(f"bs{b}", run(b, False)) for b in bs_ladder],
                  {}, "zero3")
    gc.collect()
    return _ladder([(f"bs{b}", run(b, True)) for b in bs_ladder],
                   out, "zero3_explicit")


# The hand-tuned explicit schedule the planner races against — the
# BENCH_r05 zero3 defaults (and the planner's own tie-break anchor).
HAND_DEFAULT_SCHEDULE = {"mode": "explicit", "prefetch_depth": 2,
                         "bucket_mb": 32.0, "group_layers": 4,
                         "remat": False}


def row_plan():
    """Schedule-planner row (opt-in via DS_BENCH_PLAN=1): `build_plan`
    resolves a schedule for the headline 125M shape (analytic cost
    model + memory screen; the measured probe ladder engages only where
    the kernel autotuners would probe too), then the planner-chosen
    config races the hand-default explicit schedule (prefetch 2 /
    bucket 32 MB / group 4 / no remat) on the zero3 bs ladder.
    Acceptance: plan_vs_hand_default >= 1.0."""
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    cfg, model, params = _headline_setup(jax)
    seq = min(int(os.environ.get("DS_BENCH_SEQ", "1024")),
              cfg.max_seq_len)
    bs_ladder = [int(b) for b in os.environ.get(
        "DS_BENCH_ZERO3_BS", "48,32").split(",")]
    # CPU-proxy knob: 125M steps are seconds on TPU but ~30s each on a
    # 1-core host — shrink the timing window without changing the race
    steps = int(os.environ.get("DS_BENCH_PLAN_STEPS", "12"))
    warmup = max(1, min(4, steps // 3))

    from deeperspeed_tpu.planner import build_plan
    from deeperspeed_tpu.planner.cost_model import ModelShape
    shape = ModelShape(num_layers=cfg.num_layers,
                       hidden_size=cfg.hidden_size,
                       num_heads=cfg.num_heads, seq_len=seq,
                       vocab_size=cfg.vocab_size,
                       batch_per_chip=bs_ladder[0])
    # force=True, save=False: the bench must exercise a fresh plan of
    # THIS run's shape, not whatever a previous session cached
    plan = build_plan(shape, force=True, save=False)
    plan_cfg = plan.config

    def run(bs, planned):
        def thunk():
            batch = bs * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            if planned:
                tag = "plan_chosen"
                zero_cfg = dict(plan_cfg["zero_optimization"])
                extra_cfg = {k: v for k, v in plan_cfg.items()
                             if k != "zero_optimization"}
            else:
                tag = "plan_hand_default"
                zero_cfg = {"stage": 3,
                            "schedule": dict(HAND_DEFAULT_SCHEDULE)}
                extra_cfg = None
            eng = _neox_engine(model, params, batch, zero_cfg, extra_cfg)
            dt, _ = timed_steps(eng, (tokens, tokens), steps=steps,
                                warmup=warmup)
            tps = batch * seq * steps / dt / n_chips
            return {f"{tag}_tokens_per_sec_chip": round(tps, 1),
                    f"{tag}_mfu": round(
                        tps * _flops_per_token(cfg, seq) / peak, 4)}
        return thunk

    out = {"plan_fingerprint": plan.fingerprint,
           "plan_chosen_label": plan.payload["chosen"],
           "plan_probed": plan.payload["probed"]}
    out = _ladder([(f"bs{b}", run(b, True)) for b in bs_ladder],
                  out, "plan_chosen")
    # When analytic ties resolve to the hand-tuned defaults (world=1:
    # every collective term is zero), the two race legs are the same
    # program — report the identity instead of timing the same config
    # twice and publishing scheduler noise as a ratio.
    plan_zero = plan_cfg["zero_optimization"]
    matches_hand = (plan_zero.get("schedule") == HAND_DEFAULT_SCHEDULE
                    and "offload_optimizer" not in plan_zero
                    and "quantization" not in plan_cfg)
    out["plan_matches_hand_default"] = matches_hand
    if matches_hand:
        out["plan_vs_hand_default"] = 1.0
        return out
    gc.collect()
    out = _ladder([(f"bs{b}", run(b, False)) for b in bs_ladder],
                  out, "plan_hand_default")
    chosen_tps = out.get("plan_chosen_tokens_per_sec_chip")
    hand_tps = out.get("plan_hand_default_tokens_per_sec_chip")
    if chosen_tps and hand_tps:
        out["plan_vs_hand_default"] = round(chosen_tps / hand_tps, 3)
    return out


def row_pipe():
    """Config-driven 1F1B pipeline rows (opt-in via DS_BENCH_PIPE=1):
    NeoX-125M over 2/4 pipeline stages (DS_BENCH_PIPE_STAGES), the
    remaining chips data-parallel with ZeRO-1, micro_batches =
    DS_BENCH_PIPE_MICRO. Reports tokens/s/chip (all chips, stages
    included), the analytic bubble fraction for the schedule, and a
    zero-recompile check across the measured steps. DS_BENCH_PIPE_OVERLAP
    = 1 also measures the comm_overlap (wire-latency-2) schedule."""
    jax = _setup_jax()
    from deeperspeed_tpu.parallel.schedule import bubble_fraction
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    cfg, model, params = _headline_setup(jax)
    seq = min(int(os.environ.get("DS_BENCH_SEQ", "1024")),
              cfg.max_seq_len)
    n_micro = int(os.environ.get("DS_BENCH_PIPE_MICRO", "8"))
    both_wires = os.environ.get("DS_BENCH_PIPE_OVERLAP", "1") not in (
        "0", "", "false")
    bs0 = int(os.environ.get("DS_BENCH_PIPE_BS", "48"))
    stages_sel = [int(s) for s in os.environ.get(
        "DS_BENCH_PIPE_STAGES", "2,4").split(",")]

    out = {}
    for stages in stages_sel:
        name = f"pipe{stages}"
        if n_chips % stages or cfg.num_layers % stages:
            out[f"{name}_error"] = (
                f"stages={stages} does not divide chips={n_chips} / "
                f"layers={cfg.num_layers}")
            continue
        dp = n_chips // stages
        for overlap in ([False, True] if both_wires else [False]):
            tag = f"{name}_overlap" if overlap else name

            def run(bs, stages=stages, overlap=overlap, dp=dp, tag=tag):
                def thunk():
                    bs_rank = max(n_micro, bs - bs % n_micro)
                    batch = bs_rank * dp
                    rng = np.random.default_rng(0)
                    tokens = rng.integers(0, cfg.vocab_size,
                                          size=(1, batch, seq),
                                          dtype=np.int32)
                    eng = _neox_engine(
                        model, params, batch, {"stage": 1},
                        {"pipeline": {"stages": stages,
                                      "micro_batches": n_micro,
                                      "comm_overlap": overlap}})
                    steps = 10
                    dt, _ = timed_steps(eng, (tokens, tokens),
                                        steps=steps, warmup=3)
                    compiled_before = len(eng._compiled_train)
                    eng.train_batch(batch=(tokens, tokens))
                    recompiles = len(eng._compiled_train) - \
                        compiled_before
                    tps = batch * seq * steps / dt / n_chips
                    w = 2 if overlap else 1
                    return {
                        f"{tag}_tokens_per_sec_chip": round(tps, 1),
                        f"{tag}_mfu": round(
                            tps * _flops_per_token(cfg, seq) / peak, 4),
                        f"{tag}_bubble_fraction": round(
                            bubble_fraction(stages, n_micro, w), 4),
                        f"{tag}_n_micro": n_micro,
                        f"{tag}_recompiles": recompiles,
                    }
                return thunk

            _ladder([("bs%d" % bs0, run(bs0)),
                     ("bs%d" % max(bs0 // 2, n_micro),
                      run(max(bs0 // 2, n_micro)))], out, tag)
            gc.collect()
    return out


def _bert_row(seq_len, bs_ladder):
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    import deeperspeed_tpu
    from deeperspeed_tpu.models.bert import BertConfig, BertForPreTraining
    bcfg = BertConfig.large(max_position_embeddings=max(512, seq_len))
    bmodel = BertForPreTraining(bcfg)
    bparams = bmodel.init_params(jax.random.PRNGKey(1))
    name = f"bert_large_seq{seq_len}"

    def run(bs_per_chip):
        def thunk():
            bs = bs_per_chip * n_chips
            eng, *_ = deeperspeed_tpu.initialize(
                model=bmodel, model_parameters=bparams,
                config_params={
                    "train_batch_size": bs,
                    "steps_per_print": 10_000,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "fp16": {"enabled": True, "type": "bfloat16"},
                    "zero_optimization": {"stage": 2},
                })
            r = np.random.default_rng(2)
            ids = r.integers(0, bcfg.vocab_size, (1, bs, seq_len), np.int32)
            mask = np.ones((1, bs, seq_len), np.float32)
            labels = np.where(r.random((1, bs, seq_len)) < 0.15, ids,
                              -1).astype(np.int32)
            b = {"input_ids": ids,
                 "token_type_ids": np.zeros_like(ids),
                 "attention_mask": mask,
                 "masked_lm_labels": labels,
                 "next_sentence_label": r.integers(0, 2, (1, bs), np.int32)}
            steps = 10
            dt, _ = timed_steps(eng, b, steps=steps, warmup=3)
            tps = bs * seq_len * steps / dt / n_chips
            H, L, V = bcfg.hidden_size, bcfg.num_layers, bcfg.vocab_size
            # matmul params: 12H^2/layer (qkv+out+ffn@4H) + MLM transform
            # + tied decoder; attention term 12*L*H*S (qk+pv, fwd+bwd)
            ftok = 6 * (L * 12 * H * H + H * H + H * V) + \
                12 * L * H * seq_len
            return {f"{name}_tokens_per_sec_chip": round(tps, 1),
                    f"{name}_mfu": round(tps * ftok / peak, 4),
                    f"{name}_batch_per_chip": bs_per_chip}
        return thunk

    env_bs = os.environ.get(f"DS_BENCH_BERT_BS{seq_len}")
    if env_bs:
        bs_ladder = [int(env_bs)] + [b for b in bs_ladder
                                     if b < int(env_bs)]
    return _ladder([(f"bs{b}", run(b)) for b in bs_ladder], {}, name)


def row_bert128():
    return _bert_row(128, [64, 48, 32])


def row_bert512():
    return _bert_row(512, [20, 16, 12, 8])


def _xl_prescreen(jax, xcfg, policy, nckpt, bs):
    """(fits, stats) for one (remat policy × batch) rung: AOT-compile the
    bf16 grad program over abstract shapes (`memory_analysis()`, no HBM
    touched) and add the resident optimizer state the program doesn't
    see (lean state: params-as-masters + 2 bf16 Adam moments)."""
    import jax.numpy as jnp
    from deeperspeed_tpu.models.gpt2 import GPT2
    from deeperspeed_tpu.ops.autotune import memory_feasible
    model = GPT2(xcfg, use_pallas=True, scan_blocks=True,
                 remat_policy=policy, number_checkpoints=nckpt)
    pshapes = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
    pshapes = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16), pshapes)
    toks = jax.ShapeDtypeStruct((bs, 1024), jnp.int32)

    def grad_step(p, t):
        return jax.grad(lambda q: model.loss_fn(q, (t, t)))(p)

    moments = 2 * xcfg.num_params() * 2  # 2 bf16 moments rest in HBM
    return memory_feasible(grad_step, (pshapes, toks),
                           extra_bytes=moments)


def row_gpt2xl():
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    import deeperspeed_tpu
    from deeperspeed_tpu.models.gpt2 import GPT2, GPT2Config
    xcfg = GPT2Config.megatron_1_5b()

    def run(bs_per_chip, zero_cfg, steps=2, warmup=1, lean_state=False,
            remat_policy=None, number_checkpoints=None):
        def thunk():
            # scan_blocks: one compiled block body instead of 48 —
            # the unrolled 48-layer remat program took ~20 min of XLA
            # compile; the scanned one compiles in normal time
            xmodel = GPT2(xcfg, use_pallas=True,
                          remat_blocks=remat_policy is None,
                          scan_blocks=True, remat_policy=remat_policy,
                          number_checkpoints=number_checkpoints)
            # init on the HOST cpu backend: the host-offload tier reads
            # fp32 masters host-side anyway — initializing on the chip
            # would round-trip 6.2 GB back over the (slow, tunneled)
            # link and transiently double fp32 HBM
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                xparams = xmodel.init_params(jax.random.PRNGKey(3))
            xparams = jax.tree_util.tree_map(np.asarray, xparams)
            bs = bs_per_chip * n_chips
            fp16_cfg = {"enabled": True, "type": "bfloat16"}
            opt_params = {"lr": 1e-4}
            if lean_state:
                # all-on-chip 1.5B: params-as-masters + bf16 moments
                # (~12.4 GB state vs 24.9 GB classic) — the tunneled
                # host link (~5 MB/s device→host measured) rules the
                # ZeRO-Offload tier out for per-step traffic here
                fp16_cfg["fp16_master_weights_and_grads"] = True
                opt_params["state_dtype"] = "bfloat16"
            eng, *_ = deeperspeed_tpu.initialize(
                model=xmodel, model_parameters=xparams,
                config_params={
                    "train_batch_size": bs,
                    "steps_per_print": 10_000,
                    "optimizer": {"type": "Adam", "params": opt_params},
                    "fp16": fp16_cfg,
                    "zero_optimization": zero_cfg,
                })
            del xparams
            gc.collect()
            r = np.random.default_rng(4)
            xtok = r.integers(0, xcfg.vocab_size, (1, bs, 1024), np.int32)
            dt, xl_loss = timed_steps(eng, (xtok, xtok), steps=steps,
                                      warmup=warmup)
            tps = bs * 1024 * steps / dt / n_chips
            xn = xcfg.num_params()
            xftok = 6 * xn + 12 * xcfg.num_layers * xcfg.hidden_size * 1024
            return {
                "gpt2_xl_1p5b_tokens_per_sec_chip": round(tps, 1),
                "gpt2_xl_1p5b_mfu": round(tps * xftok / peak, 4),
                "gpt2_xl_1p5b_params_b": round(xn / 1e9, 3),
                "gpt2_xl_1p5b_loss": xl_loss,
                "gpt2_xl_1p5b_batch_per_chip": bs_per_chip,
                # remat attribution: BENCH_*.json trajectories must say
                # WHICH policy/batch produced an MFU move
                "gpt2_xl_1p5b_remat_policy": remat_policy or "full",
                "gpt2_xl_1p5b_number_checkpoints": number_checkpoints,
                "gpt2_xl_1p5b_peak_rss_gb": round(
                    resource.getrusage(resource.RUSAGE_SELF).ru_maxrss /
                    1e6, 2),
            }
        return thunk

    # ------------------------------------------------------------------
    # (remat policy × batch) ladder, memory-screened: richer policies
    # (save more, recompute less) at the largest batch that FITS, walked
    # fattest-first; `memory_analysis()` on the AOT-compiled grad program
    # rejects infeasible rungs before any timed run. The legacy
    # full-remat bs4 rung stays as the floor, the ZeRO-Offload tier as
    # the final fallback.
    # ------------------------------------------------------------------
    bs0 = int(os.environ.get("DS_BENCH_XL_BS", "8"))
    # descending from bs0 (the env cap), never below the bs4 floor rung
    bs_ladder = [b for b in dict.fromkeys((bs0, 6, 4)) if b <= bs0]
    policies = [p.strip() for p in os.environ.get(
        "DS_BENCH_XL_POLICIES", "dots,attn_residuals,full").split(",")
        if p.strip()]
    nckpt_env = os.environ.get("DS_BENCH_XL_NCKPT")
    nckpt = int(nckpt_env) if nckpt_env and int(nckpt_env) > 0 else None

    ladder, screened_out, screen_errors = [], [], []
    # screening needs a known HBM budget; off-TPU the AOT compile would
    # burn minutes to learn nothing (hbm_bytes_limit() is None there)
    screen = os.environ.get("DS_BENCH_XL_SCREEN", "1") not in (
        "0", "false", "") and jax.devices()[0].platform == "tpu"
    for bs in bs_ladder:
        for pol in policies:
            if pol == "full" and bs == 4 and nckpt is None:
                continue  # that's exactly the floor rung below
            tag = f"onchip_{pol}_bs{bs}" + \
                (f"_k{nckpt}" if nckpt else "")
            if screen:
                try:
                    fits, stats = _xl_prescreen(jax, xcfg, pol, nckpt, bs)
                except Exception as e:  # noqa: BLE001 - screen, don't die
                    # the rung still RUNS (screening must never lose a
                    # viable config); record the screen failure apart
                    # from the genuinely excluded rungs
                    fits, stats = True, None
                    screen_errors.append(
                        f"{tag}: {type(e).__name__}")
                if not fits:
                    screened_out.append(
                        f"{tag}: peak {round(stats['peak'] / 2**30, 1)} "
                        "GiB over budget")
                    continue
            ladder.append((tag, run(bs, {"stage": 0}, steps=3, warmup=2,
                                    lean_state=True, remat_policy=pol,
                                    number_checkpoints=nckpt)))
    # floor: the pre-policy configuration (whole-block remat, bs4)
    ladder.append(("onchip_lean_bs4", run(4, {"stage": 0}, steps=3,
                                          warmup=2, lean_state=True)))
    # ZeRO-Offload rung last: the reference path (13B-on-one-GPU tier),
    # viable where the host link is PCIe — not over a 5 MB/s tunnel
    host_opt = {"stage": 3, "offload_optimizer": {"device": "cpu"}}
    ladder.append(("z3_hostopt_bs2", run(2, host_opt)))
    out = {}
    if screened_out:
        out["gpt2_xl_1p5b_screened_out"] = "; ".join(screened_out)[:400]
    if screen_errors:
        out["gpt2_xl_1p5b_screen_errors"] = "; ".join(screen_errors)[:300]
    return _ladder(ladder, out, "gpt2_xl_1p5b")


def _flash_block_extra(tag):
    """Record the flash dispatch geometry the LAST trace actually chose
    (fwd and bwd blocks + grid variant) so a bench round documents WHICH
    kernel configuration produced its numbers — read through the public
    `ops.dispatch_report()` accessor (the same record the telemetry
    capture exports and fleet trace metadata embed)."""
    from deeperspeed_tpu.ops import dispatch_report
    flash = dispatch_report()["flash"]
    out = {}
    fwd, bwd = flash.get("fwd"), flash.get("dkv")
    if fwd:
        out[f"{tag}_fwd_blocks"] = f"{fwd[0]}x{fwd[1]}"
        out[f"{tag}_fwd_grid"] = flash.get("fwd_variant", "?")
    if bwd:
        out[f"{tag}_bwd_blocks"] = f"{bwd[0]}x{bwd[1]}"
        out[f"{tag}_bwd_grid"] = flash.get("bwd_variant", "?")
    return out


def row_longseq():
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig

    def run(seq, bs_per_chip, engine="dense"):
        def thunk():
            lcfg = GPTNeoXConfig(vocab_size=8192, hidden_size=768,
                                 num_layers=12, num_heads=12,
                                 max_seq_len=seq)
            lmodel = GPTNeoX(lcfg, use_pallas=True, remat_blocks=True)
            lparams = lmodel.init_params(jax.random.PRNGKey(5))
            lbs = bs_per_chip * n_chips
            extra_cfg = None
            if engine == "sparse":
                # local+global fixed pattern à la the reference's
                # SparseSelfAttention: 2k-token local window + one
                # global block per window, causal. Density ~10% at 16k,
                # ~5% at 32k — well under the sparse-kernel crossover.
                extra_cfg = {"sparse_attention": {
                    "mode": "fixed", "block": 128,
                    "num_local_blocks": 16, "num_global_blocks": 1,
                    "attention": "unidirectional"}}
            eng = _neox_engine(lmodel, lparams, lbs, {"stage": 2},
                               extra_cfg=extra_cfg)
            r = np.random.default_rng(6)
            ltok = r.integers(0, lcfg.vocab_size, (1, lbs, seq), np.int32)
            dt, _ = timed_steps(eng, (ltok, ltok), steps=3, warmup=2)
            tps = lbs * seq * 3 / dt / n_chips
            ln = lcfg.num_params()
            lftok = 6 * ln + 12 * lcfg.num_layers * lcfg.hidden_size * \
                seq // 2   # causal: half the score tiles are dead
            tag = f"longseq_{seq // 1024}k"
            if engine == "sparse":
                # dense-equivalent MFU: tokens/s × DENSE flops/token —
                # the comparable "how much dense work would this pace
                # amount to" scalar (the sparse kernels burn fewer)
                return {f"{tag}_sparse_tokens_per_sec_chip": round(tps, 1),
                        f"{tag}_sparse_mfu_dense_equiv":
                            round(tps * lftok / peak, 4),
                        f"{tag}_sparse_pattern": "fixed_l16g1"}
            out = {f"{tag}_tokens_per_sec_chip": round(tps, 1),
                   f"{tag}_mfu": round(tps * lftok / peak, 4),
                   f"{tag}_remat_policy": "full",
                   f"{tag}_batch_per_chip": bs_per_chip}
            out.update(_flash_block_extra(tag))
            return out
        return thunk

    lbs = int(os.environ.get("DS_BENCH_LONG_BS", "2"))
    want_sparse = os.environ.get("DS_BENCH_LONG_SPARSE", "1") not in (
        "0", "", "false")
    out = _ladder([(f"bs{lbs}", run(16384, lbs))] +
                  ([("bs1", run(16384, 1))] if lbs > 1 else []),
                  {}, "longseq_16k")
    if "longseq_16k_mfu" in out and want_sparse:
        # block-sparse engine comparison rung at the same shape
        out = _ladder([(f"sparse_bs{lbs}", run(16384, lbs, "sparse"))],
                      out, "longseq_16k_sparse")
    if "longseq_16k_mfu" in out and \
            os.environ.get("DS_BENCH_32K", "1") not in ("0", "false"):
        # stretch row: 32k tokens (the reference claims ~10× longer
        # sequences via sparse attention; dense-flash 32k beats it).
        # Tag matches what actually runs, with a true bs1 fallback rung.
        out = _ladder([(f"bs{lbs}", run(32768, lbs))] +
                      ([("bs1", run(32768, 1))] if lbs > 1 else []),
                      out, "longseq_32k")
        if "longseq_32k_mfu" in out and want_sparse:
            out = _ladder(
                [(f"sparse_bs{lbs}", run(32768, lbs, "sparse"))],
                out, "longseq_32k_sparse")
    return out


def row_packed():
    """Packed ragged-batch row (opt-in via DS_BENCH_PACKED=1): a fixed-
    seed lognormal document mixture (`runtime.packing.
    synthetic_doc_mixture` — the shape of web corpora) greedily packed
    into 16k rows, trained with segment-aware flash kernels. The same
    packed tokens run WITHOUT segment ids as the control: identical
    shapes and flop ceiling, so the delta isolates the block-level
    cross-document skip. Effective (non-pad, non-cross-doc) tokens/s
    quantify what the padded-baseline loader would have wasted."""
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig
    from deeperspeed_tpu.runtime.packing import (
        count_effective_targets, pack_documents, synthetic_doc_mixture)

    seq = int(os.environ.get("DS_BENCH_PACKED_SEQ", str(16384)))

    def run(bs_per_chip, with_segments):
        def thunk():
            lcfg = GPTNeoXConfig(vocab_size=8192, hidden_size=768,
                                 num_layers=12, num_heads=12,
                                 max_seq_len=seq)
            lmodel = GPTNeoX(lcfg, use_pallas=True, remat_blocks=True)
            lparams = lmodel.init_params(jax.random.PRNGKey(5))
            lbs = bs_per_chip * n_chips
            extra_cfg = {"packing": {"enabled": True}} if with_segments \
                else None
            eng = _neox_engine(lmodel, lparams, lbs, {"stage": 2},
                               extra_cfg=extra_cfg)
            # fixed seed => identical mixture every round (per topology):
            # mean-2048 lognormal with a heavy tail, sized to fill lbs
            # rows of seq tokens with 75% margin (greedy packing leaves
            # partial tail rows; the guard below still backstops)
            mean_len = 2048.0
            n_docs = max(64, int(lbs * seq / mean_len * 1.75))
            docs = synthetic_doc_mixture(7, n_docs, lcfg.vocab_size,
                                         mean_len=mean_len, sigma=1.2,
                                         max_len=seq)
            tok, seg = pack_documents(docs, seq)
            if tok.shape[0] < lbs:
                raise RuntimeError(
                    f"mixture packed into {tok.shape[0]} rows < batch "
                    f"{lbs}; raise the doc count")
            tok, seg = tok[:lbs][None], seg[:lbs][None]  # [1, lbs, S]
            batch = (tok, tok, seg) if with_segments else (tok, tok)
            dt, _ = timed_steps(eng, batch, steps=3, warmup=2)
            tps = lbs * seq * 3 / dt / n_chips
            ln = lcfg.num_params()
            lftok = 6 * ln + 12 * lcfg.num_layers * lcfg.hidden_size * \
                seq // 2
            key = "packed_seg" if with_segments else "packed_noseg"
            out = {f"{key}_tokens_per_sec_chip": round(tps, 1),
                   f"{key}_mfu": round(tps * lftok / peak, 4)}
            if with_segments:
                eff = count_effective_targets(seg)
                total = int(np.prod(seg.shape[:-1])) * (seg.shape[-1] - 1)
                out["packed_occupancy"] = round(float((seg != 0).mean()), 4)
                out["packed_effective_token_fraction"] = round(
                    eff / total, 4)
                out["packed_effective_tokens_per_sec_chip"] = round(
                    tps * eff / total, 1)
                out.update(_flash_block_extra("packed"))
            return out
        return thunk

    bs0 = int(os.environ.get("DS_BENCH_PACKED_BS", "2"))
    out = _ladder([(f"bs{bs0}", run(bs0, True))] +
                  ([("bs1", run(1, True))] if bs0 > 1 else []),
                  {}, "packed")
    if "packed_seg_mfu" in out:
        bs_ran = int(out.get("packed_config", f"bs{bs0}")[2:] or bs0)
        out = _ladder([(f"bs{bs_ran}", run(bs_ran, False))], out,
                      "packed_ctl")
        if "packed_noseg_tokens_per_sec_chip" in out:
            out["packed_seg_speedup"] = round(
                out["packed_seg_tokens_per_sec_chip"] /
                out["packed_noseg_tokens_per_sec_chip"], 3)
    return out


def row_moe():
    """GShard top-2 MoE row, walked over both dispatch engines (einsum =
    reference one-hot, sort = argsort + Pallas grouped matmul). Headline
    `moe_top2_*` keys mirror the sort engine when it ran (the fast
    path), einsum otherwise; `extra` records dispatch, capacity factor
    and the configured a2a overlap depth. DS_BENCH_MOE_DISPATCH picks
    one engine ("einsum"/"sort", default both)."""
    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig

    cap_factor = float(os.environ.get("DS_BENCH_MOE_CF", "1.25"))
    a2a_chunks = int(os.environ.get("DS_BENCH_MOE_A2A_CHUNKS", "1"))

    def run(bs_per_chip, dispatch):
        def thunk():
            mcfg = GPTNeoXConfig(vocab_size=50304, hidden_size=768,
                                 num_layers=12, num_heads=12,
                                 max_seq_len=1024, moe_num_experts=8,
                                 moe_top_k=2, moe_dispatch=dispatch,
                                 moe_capacity_factor=cap_factor,
                                 moe_a2a_overlap_chunks=a2a_chunks)
            mmodel = GPTNeoX(mcfg, use_pallas=True)
            mparams = mmodel.init_params(jax.random.PRNGKey(7))
            mbs = bs_per_chip * n_chips
            eng = _neox_engine(mmodel, mparams, mbs, {"stage": 2})
            r = np.random.default_rng(8)
            mtok = r.integers(0, mcfg.vocab_size, (1, mbs, 1024),
                              np.int32)
            dt, _ = timed_steps(eng, (mtok, mtok), steps=4, warmup=2)
            tps = mbs * 1024 * 4 / dt / n_chips
            # active params/token: top-2 of 8 experts → dense-equivalent
            # flops use 2 expert FFNs per token plus the shared trunk
            H, L = mcfg.hidden_size, mcfg.num_layers
            trunk = L * 4 * H * H + mcfg.vocab_size * H
            expert = L * mcfg.moe_top_k * 8 * H * H
            mftok = 6 * (trunk + expert) + 12 * L * H * 1024
            p = f"moe_top2_{dispatch}"
            return {f"{p}_tokens_per_sec_chip": round(tps, 1),
                    f"{p}_active_mfu": round(tps * mftok / peak, 4),
                    f"{p}_batch_per_chip": bs_per_chip}
        return thunk

    sel = os.environ.get("DS_BENCH_MOE_DISPATCH", "both")
    modes = ("einsum", "sort") if sel in ("both", "", "all") else (sel,)
    bs0 = int(os.environ.get("DS_BENCH_MOE_BS", "8"))
    out = {"moe_top2_capacity_factor": cap_factor,
           "moe_top2_a2a_overlap_chunks": a2a_chunks}
    for d in modes:
        out = _ladder([(f"{d}_bs{bs0}", run(bs0, d)),
                       (f"{d}_bs4", run(4, d))], out, f"moe_top2_{d}")
    head = next((d for d in ("sort", "einsum")
                 if f"moe_top2_{d}_active_mfu" in out), None)
    if head is not None:
        out["moe_top2_dispatch"] = head
        for k in ("tokens_per_sec_chip", "active_mfu", "batch_per_chip"):
            out[f"moe_top2_{k}"] = out[f"moe_top2_{head}_{k}"]
    return out


def row_ckpt():
    """Checkpoint-induced training stall, sync vs async: how long the
    step loop blocks for a full engine save (NeoX-125M, ZeRO-2 — fp32
    masters + both Adam moments on disk). The async row also counts how
    many train steps complete while the commit is in flight. Opt-in via
    DS_BENCH_CKPT (disk-heavy; writes ~1.5 GB per save)."""
    import shutil
    import tempfile

    jax = _setup_jax()
    n_chips = len(jax.devices())
    cfg, model, params = _headline_setup(jax)
    seq = 1024

    def run(bs_per_chip):
        def thunk():
            batch = bs_per_chip * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            stacked = (tokens, tokens)
            eng = _neox_engine(model, params, batch, {"stage": 2})
            steps = 6
            dt, _ = timed_steps(eng, stacked, steps=steps, warmup=3)
            step_ms = dt / steps * 1e3
            tmp = tempfile.mkdtemp(prefix="ds_ckpt_bench_")
            try:
                # sync: the whole snapshot+serialize+commit blocks the loop
                t0 = time.perf_counter()
                eng.save_checkpoint(tmp, tag="sync")
                sync_ms = (time.perf_counter() - t0) * 1e3
                # async: only the host snapshot blocks; commit overlaps
                t0 = time.perf_counter()
                eng.save_checkpoint_async(tmp, tag="async")
                async_ms = (time.perf_counter() - t0) * 1e3
                overlapped = 0
                while eng.checkpoint_manager.in_flight and overlapped < 64:
                    eng.train_batch(batch=stacked)
                    overlapped += 1
                force(eng.state.params)
                eng.checkpoint_manager.wait()
                mgr = eng.checkpoint_manager
                return {
                    "ckpt_step_ms": round(step_ms, 1),
                    "ckpt_sync_stall_ms": round(sync_ms, 1),
                    "ckpt_async_stall_ms": round(async_ms, 1),
                    "ckpt_async_overlap_steps": overlapped,
                    "ckpt_bytes_mb": round(mgr.total_bytes / 2**20, 1),
                    "ckpt_stall_ratio": round(
                        async_ms / sync_ms, 4) if sync_ms else None,
                }
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        return thunk

    bs0 = int(os.environ.get("DS_BENCH_CKPT_BS", "16"))
    return _ladder([(f"bs{bs0}", run(bs0)), ("bs8", run(8))], {}, "ckpt")


def row_sentinel():
    """Training-health sentinel cost + recovery latency (NeoX-125M,
    ZeRO-2): step time with the sentinel off vs on (the in-jit probe +
    the per-step flags read — the acceptance bar is < 1% overhead), then
    an injected NaN-grad step under policy `rollback` measuring the full
    detect -> restore-checkpoint -> continue wall time. Opt-in via
    DS_BENCH_SENTINEL=1."""
    import shutil
    import tempfile

    jax = _setup_jax()
    n_chips = len(jax.devices())
    cfg, model, params = _headline_setup(jax)
    seq = 1024

    def engine_with(batch, tmp=None, th=None):
        import deeperspeed_tpu
        config = {
            "train_batch_size": batch,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10_000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "fp16": {"enabled": True, "type": "bfloat16"},
            "zero_optimization": {"stage": 2},
        }
        if tmp is not None:
            config["checkpoint"] = {"save_dir": tmp}
        if th is not None:
            config["training_health"] = th
        eng, *_ = deeperspeed_tpu.initialize(
            model=model, model_parameters=params, config_params=config)
        return eng

    def run(bs_per_chip):
        def thunk():
            batch = bs_per_chip * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            stacked = (tokens, tokens)
            steps = 8

            eng = engine_with(batch)
            dt_off, _ = timed_steps(eng, stacked, steps=steps, warmup=3)
            del eng
            gc.collect()

            th_on = {"enabled": True, "policy": "skip_batch",
                     "warmup_steps": 3}
            eng = engine_with(batch, th=th_on)
            dt_on, _ = timed_steps(eng, stacked, steps=steps, warmup=3)
            del eng
            gc.collect()
            overhead = (dt_on - dt_off) / dt_off

            # recovery latency: ckpt at step 3, NaN grads at step 4 ->
            # the faulted train_batch call detects, quarantines, and
            # restores the committed checkpoint before returning
            tmp = tempfile.mkdtemp(prefix="ds_sentinel_bench_")
            try:
                th_rb = {"enabled": True, "policy": "rollback",
                         "rollback_after": 1, "warmup_steps": 100,
                         "fault_injection": {"faults": [
                             {"kind": "nan_grads", "step": 4}]}}
                eng = engine_with(batch, tmp=tmp, th=th_rb)
                for _ in range(4):
                    eng.train_batch(batch=stacked)
                eng.save_checkpoint(tmp)
                force(eng.state.params)
                t0 = time.perf_counter()
                eng.train_batch(batch=stacked)   # fault -> rollback
                force(eng.state.params)
                recovery_ms = (time.perf_counter() - t0) * 1e3
                rollbacks = eng.sentinel.rollbacks
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            return {
                "sentinel_step_ms_off": round(dt_off / steps * 1e3, 2),
                "sentinel_step_ms_on": round(dt_on / steps * 1e3, 2),
                "sentinel_overhead_pct": round(overhead * 100, 2),
                "sentinel_recovery_ms": round(recovery_ms, 1),
                "sentinel_rollbacks": rollbacks,
            }
        return thunk

    bs0 = int(os.environ.get("DS_BENCH_SENTINEL_BS", "16"))
    return _ladder([(f"bs{bs0}", run(bs0)), ("bs8", run(8))], {},
                   "sentinel")


def row_telemetry():
    """Unified-telemetry cost + MFU cross-check (NeoX-125M, ZeRO-2):
    step time with the telemetry block off vs on (goodput + MFU + span
    scalars enabled, trace capture OFF — the acceptance bar is <= 1%
    overhead in that mode), plus the in-engine MFU scalar (per-variant
    `cost_analysis` flops / measured step time / peak) against this
    bench's analytic tokens/s MFU — the two methodologies must agree
    within ~2%. Opt-in via DS_BENCH_TELEMETRY=1."""
    import shutil
    import tempfile

    jax = _setup_jax()
    n_chips = len(jax.devices())
    peak = peak_flops_per_chip(jax.devices()[0])
    cfg, model, params = _headline_setup(jax)
    seq = 1024

    def engine_with(batch, tmp, telemetry=None):
        import deeperspeed_tpu
        config = {
            "train_batch_size": batch,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10_000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "fp16": {"enabled": True, "type": "bfloat16"},
            "zero_optimization": {"stage": 2},
            # both engines log scalars: the row isolates the telemetry
            # layer's cost, not the monitor's
            "tensorboard": {"enabled": True, "output_path": tmp,
                            "job_name": "bench"},
        }
        if telemetry is not None:
            config["telemetry"] = telemetry
        eng, *_ = deeperspeed_tpu.initialize(
            model=model, model_parameters=params, config_params=config)
        return eng

    def run(bs_per_chip):
        def thunk():
            batch = bs_per_chip * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            stacked = (tokens, tokens)
            steps = 8
            tmp = tempfile.mkdtemp(prefix="ds_telemetry_bench_")
            try:
                eng = engine_with(batch, tmp)
                dt_off, _ = timed_steps(eng, stacked, steps=steps,
                                        warmup=3)
                del eng
                gc.collect()

                tel_on = {"enabled": True, "goodput": True, "mfu": True,
                          "spans": True}
                eng = engine_with(batch, tmp, telemetry=tel_on)
                dt_on, _ = timed_steps(eng, stacked, steps=steps,
                                       warmup=3)
                overhead = (dt_on - dt_off) / dt_off

                tps = batch * seq * steps / dt_on / n_chips
                mfu_analytic = tps * _flops_per_token(cfg, seq) / peak
                flops = eng.telemetry.compiled_flops.get(1)
                mfu_engine = (flops / (dt_on / steps) / peak
                              if flops else None)
                frac = eng.telemetry.goodput.fraction
                del eng
                gc.collect()
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            out = {
                "telemetry_step_ms_off": round(dt_off / steps * 1e3, 2),
                "telemetry_step_ms_on": round(dt_on / steps * 1e3, 2),
                "telemetry_overhead_pct": round(overhead * 100, 2),
                "telemetry_mfu_analytic": round(mfu_analytic, 4),
                "telemetry_goodput_fraction": round(frac, 4),
            }
            if mfu_engine is not None:
                out["telemetry_mfu_in_engine"] = round(mfu_engine, 4)
                out["telemetry_mfu_ratio"] = round(
                    mfu_engine / mfu_analytic, 4)
            return out
        return thunk

    bs0 = int(os.environ.get("DS_BENCH_TELEMETRY_BS", "16"))
    return _ladder([(f"bs{bs0}", run(bs0)), ("bs8", run(8))], {},
                   "telemetry")


def row_fleet():
    """Fleet observability row (opt-in via DS_BENCH_FLEET=1, NeoX-125M,
    ZeRO-2): (a) telemetry overhead with fleet scalars + the Prometheus
    exporter ON (capture off) vs the telemetry block absent — the
    acceptance bar is <= 1% step time; (b) straggler detection: an
    injected `slow_peer` fault (the PR 9 fault kind) must be NAMED by
    the collective-skew probe, recording the detection latency in steps
    and the named-host correctness; (c) a live scrape of the Prometheus
    endpoint counting the Train/* families served."""
    import shutil
    import tempfile
    import urllib.request

    jax = _setup_jax()
    n_chips = len(jax.devices())
    cfg, model, params = _headline_setup(jax)
    seq = 1024

    def engine_with(batch, tmp, fleet=False, fault_step=None):
        import deeperspeed_tpu
        config = {
            "train_batch_size": batch,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10_000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "fp16": {"enabled": True, "type": "bfloat16"},
            "zero_optimization": {"stage": 2},
            "tensorboard": {"enabled": True, "output_path": tmp,
                            "job_name": "bench"},
        }
        if fleet:
            config["telemetry"] = {
                "enabled": True, "goodput": True, "mfu": False,
                "spans": True,
                "fleet": {"enabled": True, "window_steps": 4,
                          "skew_interval_steps": 2,
                          "skew_slow_threshold_ms": 100.0}}
            config["monitor"] = {"export": {"prometheus_port": 0}}
            config["elasticity"] = {"heartbeat": {
                "enabled": True, "interval_s": 0.2,
                "warn_after_s": 60.0, "fail_after_s": 600.0}}
        if fault_step is not None:
            config["training_health"] = {"fault_injection": {"faults": [
                {"kind": "slow_peer", "step": fault_step,
                 "seconds": 0.25}]}}
        eng, *_ = deeperspeed_tpu.initialize(
            model=model, model_parameters=params, config_params=config)
        return eng

    def run(bs_per_chip):
        def thunk():
            batch = bs_per_chip * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            stacked = (tokens, tokens)
            steps = 8
            tmp = tempfile.mkdtemp(prefix="ds_fleet_bench_")
            try:
                eng = engine_with(batch, tmp)
                dt_off, _ = timed_steps(eng, stacked, steps=steps,
                                        warmup=3)
                del eng
                gc.collect()

                eng = engine_with(batch, tmp, fleet=True)
                dt_on, _ = timed_steps(eng, stacked, steps=steps,
                                       warmup=3)
                overhead = (dt_on - dt_off) / dt_off
                prom = eng.monitor.prometheus
                eng.monitor.flush()
                families = 0
                if prom is not None:
                    body = urllib.request.urlopen(
                        f"http://127.0.0.1:{prom.port}/metrics",
                        timeout=5).read().decode()
                    families = sum(1 for line in body.splitlines()
                                   if line.startswith("# TYPE ds_train_"))
                if eng.peer_monitor is not None:
                    eng.peer_monitor.stop()
                eng.monitor.close()
                del eng
                gc.collect()

                # straggler detection: slow_peer fires at step 3; the
                # skew probe (every 2 steps) must NAME the simulated
                # host — detection latency = steps from fire to naming
                fault_step = 3
                eng = engine_with(batch, tmp, fleet=True,
                                  fault_step=fault_step)
                from deeperspeed_tpu.runtime.fault_injection import \
                    DEFAULT_SIM_PEER
                detected_at = None
                for i in range(10):
                    eng.train_batch(batch=stacked)
                    fleet = eng.telemetry.fleet
                    if detected_at is None and fleet is not None and \
                            fleet.last_slowest == DEFAULT_SIM_PEER:
                        detected_at = i + 1
                        break
                named_ok = detected_at is not None
                eng.peer_monitor.stop()
                eng.monitor.close()
                del eng
                gc.collect()
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            out = {
                "fleet_step_ms_off": round(dt_off / steps * 1e3, 2),
                "fleet_step_ms_on": round(dt_on / steps * 1e3, 2),
                "fleet_overhead_pct": round(overhead * 100, 2),
                "fleet_prom_train_families": families,
                "fleet_slow_peer_named": bool(named_ok),
            }
            if detected_at is not None:
                out["fleet_detect_latency_steps"] = \
                    detected_at - fault_step
            return out
        return thunk

    bs0 = int(os.environ.get("DS_BENCH_FLEET_BS", "16"))
    return _ladder([(f"bs{bs0}", run(bs0))], {}, "fleet")


def row_serve():
    """Continuous-batching serving row (opt-in via DS_BENCH_SERVE=1): a
    fixed-seed open-loop request stream (lognormal prompt lengths,
    arrivals every other scheduler step regardless of progress) through
    the InferenceEngine — NeoX-125M, greedy decode, paged KV cache,
    single-bucket prefill/decode batch shapes so warmup compiles
    exactly one program per prefill length plus one decode program.
    Reports generated tokens/s/chip, p50/p99 inter-token latency, p50
    time-to-first-token, and the compile-count delta over the measured
    stream (the zero-recompile discipline: must be 0)."""
    jax = _setup_jax()
    cfg, model, params = _headline_setup(jax)

    def run(n_req):
        def thunk():
            from deeperspeed_tpu.inference import InferenceEngine
            max_batch = int(os.environ.get("DS_BENCH_SERVE_BATCH", "16"))
            max_new = int(os.environ.get("DS_BENCH_SERVE_NEW", "64"))
            conf = {"inference": {
                "enabled": True, "page_size": 64,
                "num_pages": int(os.environ.get("DS_BENCH_SERVE_PAGES",
                                                "513")),
                "max_batch_size": max_batch, "token_budget": 2048,
                "prefill_batch_sizes": [4],
                "decode_batch_sizes": [max_batch]}}
            eng = InferenceEngine(model, config=conf, params=params)
            rng = np.random.default_rng(0)
            hi = min(768, eng.prefill_lengths[-1],
                     eng.max_seq_len - max_new)
            lens = np.clip(np.exp(rng.normal(5.0, 0.8, size=n_req)),
                           8, hi).astype(int)
            prompts = [list(rng.integers(1, cfg.vocab_size, size=int(n)))
                       for n in lens]

            # warm every prefill length bucket + the decode program so
            # the measured stream starts fully compiled (b - 2 so the
            # top bucket's prompt + 2 tokens still fits the window)
            eng.generate([list(rng.integers(1, cfg.vocab_size, size=b - 2))
                          for b in eng.prefill_lengths], max_new_tokens=2)
            compiled_warm = eng.compile_count()
            # measured-stream deltas only: the warmup pass's counters
            # include per-bucket compile time in its prefill span
            warm_stats = dict(eng.stats)

            t_start = time.perf_counter()
            submit_at, last, seen = {}, {}, {}
            itl, ttft = [], []
            submitted = 0
            step = 0
            while submitted < len(prompts) or eng.scheduler.has_work:
                while submitted < len(prompts) and submitted * 2 <= step:
                    rid = eng.submit(prompts[submitted],
                                     max_new_tokens=max_new)
                    submit_at[rid] = time.perf_counter()
                    submitted += 1
                if eng.scheduler.has_work:
                    eng.step()
                now = time.perf_counter()
                for r in list(eng.scheduler.running) + \
                        eng.scheduler.finished:
                    rid = r.request_id
                    if rid not in submit_at:
                        continue                      # warmup requests
                    k = len(r.generated)
                    if k > seen.get(rid, 0):
                        if rid in last:
                            itl.append(now - last[rid])
                        else:
                            ttft.append(now - submit_at[rid])
                        last[rid] = now
                        seen[rid] = k
                step += 1
            dt = time.perf_counter() - t_start
            stats = {k: v - warm_stats[k] for k, v in eng.stats.items()}
            gen = sum(len(r.generated) for r in eng.scheduler.finished
                      if r.request_id in submit_at)
            def pct(vals, q):
                # DS_BENCH_SERVE_NEW=1 yields no inter-token intervals
                # (every request finishes at prefill) — report null,
                # don't kill the row
                if not vals:
                    return None
                return round(float(np.percentile(np.asarray(vals), q))
                             * 1e3, 2)

            return {
                # serving runs on one chip unless a mesh is attached
                "serve_tokens_per_s_chip": round(gen / dt, 1),
                "serve_chips": 1,
                # precision identity: BENCH history needs to attribute
                # serving deltas to weight/compute/KV dtype changes
                # (docs/quantization.md)
                "serve_weight_dtype": eng.dtypes["weight"],
                "serve_compute_dtype": eng.dtypes["compute"],
                "serve_kv_dtype": eng.dtypes["kv_cache"],
                "serve_p50_token_ms": pct(itl, 50),
                "serve_p99_token_ms": pct(itl, 99),
                "serve_ttft_p50_ms": pct(ttft, 50),
                "serve_requests": n_req,
                "serve_gen_tokens": gen,
                "serve_steps": stats["steps"],
                "serve_evictions": stats["evictions"],
                "serve_prefill_s": round(stats["prefill_s"], 2),
                "serve_decode_s": round(stats["decode_s"], 2),
                "serve_compile_delta": eng.compile_count() - compiled_warm,
            }
        return thunk

    n0 = int(os.environ.get("DS_BENCH_SERVE_REQUESTS", "64"))
    return _ladder([(f"req{n0}", run(n0)), ("req16", run(16))], {},
                   "serve")


def row_serve_chaos():
    """Serving-under-failure row (opt-in DS_BENCH_SERVE_CHAOS=1): the
    fixed-seed open-loop serve stream run twice — CLEAN (robustness
    layer on, no faults firing) and under a scripted FAULT STORM
    (injected decode errors, a decode stall, page-pool pressure)
    against a bounded admission queue. Reports per-variant success
    rate, shed fraction, and p99 TTFT (plus the storm-vs-clean p99
    TTFT degradation), and pins the chaos invariants: the server never
    exits, every accepted request reaches exactly one terminal status,
    zero KV pages leak, zero post-warmup recompiles."""
    jax = _setup_jax()
    cfg, model, params = _headline_setup(jax)

    def run(n_req, faults, prefix):
        def thunk():
            from deeperspeed_tpu.inference import (InferenceEngine,
                                                   RequestRejected)
            max_batch = int(os.environ.get("DS_BENCH_SERVE_BATCH", "16"))
            max_new = int(os.environ.get("DS_BENCH_SERVE_NEW", "64"))
            block = {
                "enabled": True, "page_size": 64,
                "num_pages": int(os.environ.get("DS_BENCH_SERVE_PAGES",
                                                "513")),
                "max_batch_size": max_batch, "token_budget": 2048,
                "prefill_batch_sizes": [4],
                "decode_batch_sizes": [max_batch],
                "admission": {"max_queue_depth": int(os.environ.get(
                    "DS_BENCH_SERVE_CHAOS_QUEUE", "24"))},
                "retry": {"max_attempts": 3, "backoff_base_ms": 5,
                          "backoff_cap_ms": 50, "jitter": 0.25},
            }
            if faults:
                block["fault_injection"] = {"faults": faults}
            eng = InferenceEngine(model, config={"inference": block},
                                  params=params)
            rng = np.random.default_rng(0)
            hi = min(768, eng.prefill_lengths[-1],
                     eng.max_seq_len - max_new)
            lens = np.clip(np.exp(rng.normal(5.0, 0.8, size=n_req)),
                           8, hi).astype(int)
            prompts = [list(rng.integers(1, cfg.vocab_size, size=int(n)))
                       for n in lens]
            eng.generate([list(rng.integers(1, cfg.vocab_size, size=b - 2))
                          for b in eng.prefill_lengths], max_new_tokens=2)
            compiled_warm = eng.compile_count()
            base = {k: eng.stats[k] for k in
                    ("requests_ok", "requests_deadline_exceeded",
                     "requests_failed")}

            submit_at, first_tok = {}, {}
            shed = 0
            submitted = 0
            step = 0
            died = None
            t_start = time.perf_counter()
            while submitted < len(prompts) or eng.scheduler.has_work:
                while submitted < len(prompts) and submitted * 2 <= step:
                    try:
                        rid = eng.submit(prompts[submitted],
                                         max_new_tokens=max_new)
                        submit_at[rid] = time.perf_counter()
                    except RequestRejected:
                        shed += 1
                    submitted += 1
                if eng.scheduler.has_work:
                    try:
                        eng.step()
                    except BaseException as e:  # noqa: BLE001
                        died = f"{type(e).__name__}: {e}"
                        break
                now = time.perf_counter()
                for r in list(eng.scheduler.running) + \
                        eng.scheduler.finished:
                    rid = r.request_id
                    if rid in submit_at and rid not in first_tok and \
                            r.generated:
                        first_tok[rid] = now - submit_at[rid]
                step += 1
                if time.perf_counter() - t_start > 600:
                    died = "stream timed out"
                    break
            gen = sum(len(r.generated) for r in eng.scheduler.finished
                      if r.request_id in submit_at)
            dt = time.perf_counter() - t_start
            accepted = len(submit_at)
            terminal = sum(eng.stats[k] - base[k] for k in base)
            ttft = sorted(first_tok.values())

            def pct(vals, q):
                if not vals:
                    return None
                return round(float(np.percentile(np.asarray(vals), q))
                             * 1e3, 2)

            return {
                f"{prefix}requests": submitted,
                f"{prefix}success_rate": round(
                    (eng.stats["requests_ok"] - base["requests_ok"]) /
                    max(submitted, 1), 4),
                f"{prefix}shed_fraction": round(
                    shed / max(submitted, 1), 4),
                f"{prefix}ttft_p50_ms": pct(ttft, 50),
                f"{prefix}ttft_p99_ms": pct(ttft, 99),
                f"{prefix}tokens_per_s": round(gen / dt, 1),
                f"{prefix}quarantines": eng.stats["quarantines"],
                f"{prefix}evictions": eng.stats["evictions"],
                # invariants — all must hold for the row to mean anything
                f"{prefix}server_up": died is None,
                f"{prefix}died": died,
                f"{prefix}all_terminal": terminal == accepted,
                f"{prefix}pages_leaked":
                    (eng.cache.num_pages - 1) - eng.cache.num_free,
                f"{prefix}compile_delta":
                    eng.compile_count() - compiled_warm,
            }
        return thunk

    n0 = int(os.environ.get("DS_BENCH_SERVE_REQUESTS", "64"))
    # the storm script scales with the stream: errors early and late,
    # a stall mid-stream, pool pressure across a burst window
    storm = [
        {"kind": "decode_error", "step": 40, "times": 2},
        {"kind": "decode_error", "step": 120, "times": 1},
        {"kind": "decode_stall", "step": 80, "seconds": 0.05},
        {"kind": "page_pool_pressure", "step": 60, "times": 5,
         "factor": 0.7},
    ]
    out = {}
    _ladder([("clean", run(n0, None, "chaos_clean_"))], out,
            "serve_chaos_clean")
    _ladder([("storm", run(n0, storm, "chaos_storm_"))], out,
            "serve_chaos_storm")
    p99c = out.get("chaos_clean_ttft_p99_ms")
    p99s = out.get("chaos_storm_ttft_p99_ms")
    if p99c and p99s:
        # the headline number: how much tail TTFT the fault storm costs
        out["chaos_ttft_p99_degradation_pct"] = round(
            (p99s - p99c) / p99c * 100.0, 1)
    return out


def row_serve_prefix():
    """Prefix-cache + speculative-decode serving row (opt-in via
    DS_BENCH_SERVE_PREFIX=1): a bursty stream where 80% of the prompts
    share one long prefix — the archetypal system-prompt fleet — run
    through (1) a cache-off baseline engine and (2) an engine with the
    prefix registry AND a small draft model, measured on its third
    stream (two warmup streams: the first compiles the miss-path
    buckets, the second the registry-hit chunk buckets — steady state
    from there, pinned by serve_prefix_compile_delta == 0). Reports the
    prefix hit rate, effective prefill tokens/s for both engines (full
    context tokens per prefill-wall-second — shared pages make the
    cache-on number rise above the compute rate), the speculative
    acceptance rate, and the p50 inter-token speedup vs the
    non-speculative baseline."""
    jax = _setup_jax()
    cfg, model, params = _headline_setup(jax)

    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig

    # the draft: same vocab/window, a fraction of the depth/width — big
    # enough to agree with the target often, cheap enough that a k-step
    # propose costs less than the verified forward it saves
    draft_cfg = GPTNeoXConfig(vocab_size=cfg.vocab_size, hidden_size=256,
                              num_layers=4, num_heads=8,
                              max_seq_len=cfg.max_seq_len)
    draft = GPTNeoX(draft_cfg, use_pallas=True)
    draft_params = draft.init_params(jax.random.PRNGKey(3))

    max_new = int(os.environ.get("DS_BENCH_SERVE_NEW", "32"))
    n_req = int(os.environ.get("DS_BENCH_SERVE_REQUESTS", "32"))
    prefix_len = int(os.environ.get("DS_BENCH_SERVE_PREFIX_LEN", "256"))
    spec_k = int(os.environ.get("DS_BENCH_SERVE_SPEC_K", "4"))

    def make_prompts(rng, shared):
        out = []
        for i in range(n_req):
            tail = list(rng.integers(
                1, cfg.vocab_size, size=int(rng.integers(8, 48))))
            if i % 5 == 4:                   # 20% cold prompts
                out.append(list(rng.integers(
                    1, cfg.vocab_size, size=prefix_len)) + tail)
            else:
                out.append(shared + tail)
        return out

    def stream(eng, prompts):
        """One bursty stream: submit everything, drain, return wall
        inter-token p50 + the engine-stats deltas."""
        before = dict(eng.stats)
        last, itl = {}, []
        for p in prompts:
            eng.submit(p, max_new_tokens=max_new)
        while eng.scheduler.has_work:
            eng.step()
            now = time.perf_counter()
            for r in list(eng.scheduler.running):
                k = len(r.generated)
                if k and r.request_id in last and \
                        k > last[r.request_id][1]:
                    # spec appends several tokens per step: one step's
                    # gap amortizes over every token it appended
                    gap = (now - last[r.request_id][0]) / \
                        (k - last[r.request_id][1])
                    itl.extend([gap] * (k - last[r.request_id][1]))
                if k:
                    last[r.request_id] = (now, k)
        eng.scheduler.pop_finished()
        delta = {k: v - before[k] for k, v in eng.stats.items()
                 if isinstance(v, (int, float))}
        p50 = float(np.percentile(np.asarray(itl), 50)) if itl else None
        return delta, p50

    def thunk():
        from deeperspeed_tpu.inference import InferenceEngine
        base_block = {
            "enabled": True, "page_size": 64,
            "num_pages": int(os.environ.get("DS_BENCH_SERVE_PAGES",
                                            "513")),
            "max_batch_size": 8, "token_budget": 2048,
            "prefill_batch_sizes": [4], "decode_batch_sizes": [8]}
        rng = np.random.default_rng(0)
        # ONE shared prefix for the whole row — the registry warms on
        # stream one and every later shared prompt hits it
        shared = list(rng.integers(1, cfg.vocab_size, size=prefix_len))

        base = InferenceEngine(model, config={"inference": base_block},
                               params=params)
        stream(base, make_prompts(rng, shared))        # warmup
        base_delta, base_p50 = stream(base, make_prompts(rng, shared))

        both_block = dict(base_block)
        both_block["prefix_cache"] = {"enabled": True}
        both_block["speculative"] = {"enabled": True,
                                     "num_draft_tokens": spec_k}
        eng = InferenceEngine(model, config={"inference": both_block},
                              params=params, draft_model=draft,
                              draft_params=draft_params)
        stream(eng, make_prompts(rng, shared))         # warmup 1: misses
        stream(eng, make_prompts(rng, shared))         # warmup 2: hits
        warm = eng.compile_count()
        pcs_before = dict(eng.prefix_cache.stats)
        delta, p50 = stream(eng, make_prompts(rng, shared))

        pcs = {k: v - pcs_before[k]
               for k, v in eng.prefix_cache.stats.items()}
        out = {
            "serve_prefix_requests": n_req,
            "serve_prefix_shared_len": prefix_len,
            "serve_prefix_hit_rate": round(
                pcs["lookups"] and pcs["hits"] / pcs["lookups"], 3),
            "serve_prefix_saved_tokens": pcs["saved_prefill_tokens"],
            # effective prefill throughput: FULL context tokens per
            # prefill-wall-second (the cache-on engine only computes
            # the unshared suffixes, so its effective rate rises)
            "serve_prefix_base_prefill_tok_s": round(
                base_delta["prefill_tokens"] /
                max(base_delta["prefill_s"], 1e-9), 1),
            "serve_prefix_prefill_tok_s": round(
                delta["prefill_tokens"] /
                max(delta["prefill_s"], 1e-9), 1),
            "serve_prefix_spec_acceptance": round(
                delta["spec_proposed"] and
                delta["spec_accepted"] / delta["spec_proposed"], 3),
            "serve_prefix_base_p50_token_ms": round(base_p50 * 1e3, 2),
            "serve_prefix_p50_token_ms": round(p50 * 1e3, 2),
            "serve_prefix_p50_speedup": round(base_p50 / p50, 2),
            # steady-state pin: the measured stream compiled nothing
            "serve_prefix_compile_delta": eng.compile_count() - warm,
        }
        return out

    return _ladder([("neox125m", thunk)], {}, "serve_prefix")


def row_serve_disagg():
    """Disaggregated prefill/decode serving row (opt-in via
    DS_BENCH_SERVE_DISAGG=1): the bursty 80%-shared-prefix stream run
    through (1) a unified engine and (2) a prefill-pool + decode-pool
    split over the in-memory handoff transport, both with the prefix
    registry on. Two warmup streams per layout (the split needs both:
    the first warms the outbox-batched install buckets, the second the
    announcement-live staggered ones), then one measured stream.
    Reports generated tokens/s for both layouts, the decode-side
    p50/p99 inter-token latency while the prefill bursts land (the
    cadence isolation the split buys), the handoff round-trip p50 ms,
    and the post-warmup compile delta summed over BOTH pools (the
    steady-state pin — must be 0)."""
    jax = _setup_jax()
    cfg, model, params = _headline_setup(jax)

    max_new = int(os.environ.get("DS_BENCH_SERVE_NEW", "32"))
    n_req = int(os.environ.get("DS_BENCH_SERVE_REQUESTS", "32"))
    prefix_len = int(os.environ.get("DS_BENCH_SERVE_PREFIX_LEN", "256"))

    def make_prompts(rng, shared):
        out = []
        for i in range(n_req):
            tail = list(rng.integers(
                1, cfg.vocab_size, size=int(rng.integers(8, 48))))
            if i % 5 == 4:                   # 20% cold prompts
                out.append(list(rng.integers(
                    1, cfg.vocab_size, size=prefix_len)) + tail)
            else:
                out.append(shared + tail)
        return out

    def stream(front, decoder, engines, prompts):
        """One bursty stream: submit on ``front``, step every engine
        in lockstep, collect inter-token gaps on ``decoder``'s running
        set (for the split that is the decode pool only). Returns
        (wall_s, generated_tokens, itl_gaps)."""
        last, itl = {}, []
        t0 = time.perf_counter()
        for p in prompts:
            front.submit(p, max_new_tokens=max_new)
        while any(e.scheduler.has_work or
                  getattr(e, "_handoff_outbox", None) or
                  getattr(e, "_pending_handoff", None)
                  for e in engines):
            for e in engines:
                e.step()
            now = time.perf_counter()
            for r in list(decoder.scheduler.running):
                k = len(r.generated)
                if k and r.request_id in last and \
                        k > last[r.request_id][1]:
                    itl.append(now - last[r.request_id][0])
                if k:
                    last[r.request_id] = (now, k)
        wall = time.perf_counter() - t0
        finished = [r for e in engines
                    for r in e.scheduler.pop_finished()]
        assert len(finished) == n_req, (len(finished), n_req)
        tokens = sum(len(r.generated) for r in finished)
        return wall, tokens, itl

    def thunk():
        from deeperspeed_tpu.elasticity.heartbeat import \
            InMemoryTransport
        from deeperspeed_tpu.inference import InferenceEngine
        base_block = {
            "enabled": True, "page_size": 64,
            "num_pages": int(os.environ.get("DS_BENCH_SERVE_PAGES",
                                            "513")),
            "max_batch_size": 8, "token_budget": 2048,
            "prefill_batch_sizes": [4], "decode_batch_sizes": [8],
            "prefix_cache": {"enabled": True}}
        rng = np.random.default_rng(0)
        shared = list(rng.integers(1, cfg.vocab_size, size=prefix_len))

        uni = InferenceEngine(model, config={"inference": base_block},
                              params=params)
        for _ in range(2):                                   # warmup
            stream(uni, uni, [uni], make_prompts(rng, shared))
        uni_warm = uni.compile_count()
        uni_wall, uni_tokens, uni_itl = stream(
            uni, uni, [uni], make_prompts(rng, shared))

        t = InMemoryTransport()
        pools = {}
        for role in ("prefill", "decode"):
            block = dict(base_block)
            block["disaggregation"] = {"role": role,
                                       "pool_id": f"{role[:3]}0"}
            pools[role] = InferenceEngine(
                model, config={"inference": block}, params=params,
                handoff_transport=t)
        pre, dec = pools["prefill"], pools["decode"]
        for _ in range(2):                                   # warmup
            stream(pre, dec, [pre, dec], make_prompts(rng, shared))
        warm = pre.compile_count() + dec.compile_count()
        acked_before = pre.stats["handoff_acked"]
        wall, tokens, itl = stream(pre, dec, [pre, dec],
                                   make_prompts(rng, shared))

        itl_ms = np.asarray(itl) * 1e3
        uni_itl_ms = np.asarray(uni_itl) * 1e3
        return {
            "serve_disagg_requests": n_req,
            "serve_disagg_shared_len": prefix_len,
            "serve_disagg_unified_tok_s": round(uni_tokens /
                                                max(uni_wall, 1e-9), 1),
            "serve_disagg_tok_s": round(tokens / max(wall, 1e-9), 1),
            "serve_disagg_unified_p50_token_ms": round(
                float(np.percentile(uni_itl_ms, 50)), 2),
            "serve_disagg_unified_p99_token_ms": round(
                float(np.percentile(uni_itl_ms, 99)), 2),
            "serve_disagg_p50_token_ms": round(
                float(np.percentile(itl_ms, 50)), 2),
            "serve_disagg_p99_token_ms": round(
                float(np.percentile(itl_ms, 99)), 2),
            "serve_disagg_handoffs": pre.stats["handoff_acked"] -
                acked_before,
            "serve_disagg_handoff_p50_ms":
                pre.serve_stats().get("handoff_p50_ms"),
            # steady-state pin across BOTH pools
            "serve_disagg_compile_delta":
                pre.compile_count() + dec.compile_count() - warm,
        }

    return _ladder([("neox125m", thunk)], {}, "serve_disagg")


_ELASTIC_WORKER = '''
import json, os, sys, time
workdir, target, crash = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
restart = int(os.environ.get("DS_ELASTIC_RESTART_COUNT", "0") or 0)
import numpy as np
import jax, jax.numpy as jnp
import deeperspeed_tpu

D = 64
def loss_fn(params, batch, rng):
    x, y = batch
    h = jnp.tanh(x @ params["w1"])
    return jnp.mean((h @ params["w2"] - y) ** 2)

k1, k2 = jax.random.split(jax.random.PRNGKey(0))
params = {"w1": jax.random.normal(k1, (D, D)) * 0.1,
          "w2": jax.random.normal(k2, (D, D)) * 0.1}
ckpt = os.path.join(workdir, "ckpt")
engine, *_ = deeperspeed_tpu.initialize(
    model=loss_fn, model_parameters=params,
    config_params={"train_batch_size": 8, "steps_per_print": 100000,
                   "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                   "checkpoint": {"save_dir": ckpt, "async_save": False,
                                  "save_interval_steps": 2}})
resumed = None
if os.path.exists(os.path.join(ckpt, "latest")):
    path, _ = engine.load_checkpoint(ckpt)
    assert path is not None
    resumed = engine.global_steps
events = open(os.path.join(workdir, "events.jsonl"), "a")
while engine.global_steps < target:
    s = engine.global_steps
    r = np.random.default_rng(s)          # batch keyed by step: resume
    x = r.normal(size=(1, 8, D)).astype(np.float32)   # replays the
    y = r.normal(size=(1, 8, D)).astype(np.float32)   # exact stream
    loss = engine.train_batch(batch=(x, y))
    events.write(json.dumps({"restart": restart,
                             "step": engine.global_steps,
                             "t": time.time(), "resumed_from": resumed,
                             "loss": float(loss)}) + "\\n")
    events.flush()
    if restart == 0 and crash and engine.global_steps == crash:
        os._exit(3)                       # hard kill: no cleanup
'''


def row_elastic():
    """Supervised-restart recovery (opt-in via DS_BENCH_ELASTIC=1): a
    tiny training job under `elasticity.supervisor.Supervisor` is
    hard-killed (os._exit — the single-host stand-in for a preempted
    host) mid-run; the row reports the kill -> resumed-step wall clock
    (MTTR: crash detection + backoff + process relaunch + jax bring-up
    + checkpoint load + recompile) and the steps lost to the
    uncommitted window (save interval 2 -> at most 1)."""
    import shutil
    import tempfile

    from deeperspeed_tpu.elasticity import constants as ec
    from deeperspeed_tpu.elasticity.supervisor import Supervisor

    target = int(os.environ.get("DS_BENCH_ELASTIC_STEPS", "12"))
    crash = int(os.environ.get("DS_BENCH_ELASTIC_CRASH_STEP", "7"))
    workdir = tempfile.mkdtemp(prefix="ds_elastic_bench_")
    try:
        worker = os.path.join(workdir, "worker.py")
        with open(worker, "w") as f:
            f.write(_ELASTIC_WORKER)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = env.get("DS_BENCH_ELASTIC_PLATFORM",
                                       env.get("JAX_PLATFORMS", ""))
        # the worker runs from the temp dir: put this repo on its path,
        # and scrub any leaked rendezvous vars (the child is single-host)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.abspath(__file__))] +
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        for var in ("RANK", "WORLD_SIZE", "LOCAL_RANK", "NODE_RANK",
                    "MASTER_ADDR", "MASTER_PORT", "DS_SLOTS"):
            env.pop(var, None)
        sup = Supervisor(
            [sys.executable, worker, workdir, str(target), str(crash)],
            os.path.join(workdir, "state"), env=env, max_restarts=2,
            backoff_base_s=float(os.environ.get(
                "DS_BENCH_ELASTIC_BACKOFF", "0.5")),
            backoff_max_s=4.0, backoff_jitter=0.0)
        t0 = time.perf_counter()
        stats = sup.run()
        total_s = time.perf_counter() - t0
        if stats["exit_code"] != 0 or stats["restarts"] != 1:
            return {"elastic_error": f"unexpected run: {stats}"}

        events = [json.loads(line) for line in
                  open(os.path.join(workdir, "events.jsonl"))]
        record = json.load(open(os.path.join(
            workdir, "state", ec.SUPERVISOR_FILE)))
        resumed = [e for e in events if e["restart"] == 1]
        first_resumed = resumed[0]
        recovery_s = first_resumed["t"] - record["crash_time"]
        steps_lost = crash - int(first_resumed["resumed_from"])
        # trajectory check: replayed steps match the first incarnation
        first_by_step = {e["step"]: e["loss"] for e in events
                         if e["restart"] == 0}
        aligned = all(
            abs(e["loss"] - first_by_step[e["step"]]) <= 1e-6
            for e in resumed if e["step"] in first_by_step)
        return {
            "elastic_recovery_s": round(recovery_s, 2),
            "elastic_steps_lost": steps_lost,
            "elastic_backoff_s": round(stats["total_backoff_s"], 2),
            "elastic_total_s": round(total_s, 2),
            "elastic_crash_step": crash,
            "elastic_resumed_from": int(first_resumed["resumed_from"]),
            "elastic_trajectory_aligned": bool(aligned),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def row_offload():
    """Tiered-offload row (opt-in via DS_BENCH_OFFLOAD=1): the explicit
    schedule run three ways — on-chip (the extrapolation baseline),
    host-DRAM rows (offload_param+offload_optimizer cpu, double-buffered
    prefetch), and NVMe rows when DS_BENCH_OFFLOAD_NVME names a path —
    with step time, prefetch-stall fraction and h2d/d2h wire volume per
    tier, plus a synthetic beyond-HBM rung: a model sized
    DS_BENCH_OFFLOAD_RATIO x device HBM (fallback
    DS_BENCH_OFFLOAD_SYNTH_GB when the backend reports no bytes_limit,
    e.g. the CPU lane) trains on the host-DRAM tier, and its measured
    step time is compared against the on-chip row extrapolated by the
    flops ratio (`offload_synth_overlap_fraction` — the >0.8 target)."""
    jax = _setup_jax()
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig
    n_chips = len(jax.devices())
    cfg, model, params = _headline_setup(jax)
    seq = min(int(os.environ.get("DS_BENCH_SEQ", "1024")),
              cfg.max_seq_len)
    bs = int(os.environ.get("DS_BENCH_OFFLOAD_BS", "8"))
    batch = bs * n_chips
    prefetch = int(os.environ.get("DS_BENCH_OFFLOAD_PREFETCH", "2"))
    group = int(os.environ.get("DS_BENCH_OFFLOAD_GROUP", "4"))
    steps = int(os.environ.get("DS_BENCH_OFFLOAD_STEPS", "6"))
    sched = {"mode": "explicit", "prefetch_depth": prefetch,
             "group_layers": group}
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                          dtype=np.int32)
    out = {"offload_prefetch_depth": prefetch,
           "offload_group_layers": group,
           "offload_batch_per_chip": bs, "offload_seq": seq}

    def run(tag, zero_cfg, mdl=None, prm=None, toks=None, n_steps=steps,
            warmup=2, bsize=None):
        def thunk():
            eng = _neox_engine(mdl or model, prm if prm is not None
                               else params, bsize or batch, zero_cfg)
            t = toks if toks is not None else tokens
            # warmup OUTSIDE timed_steps, then snapshot the offload
            # counters: compile-time waits and cold first uploads would
            # otherwise inflate the stall fraction / wire volume of the
            # timed window
            for _ in range(warmup):
                eng.train_batch(batch=(t, t))
            base = dict(getattr(eng, "_offload_totals", {}))
            dt, loss = timed_steps(eng, (t, t), steps=n_steps, warmup=0)
            res = {f"offload_{tag}_step_ms": round(dt / n_steps * 1e3, 1),
                   f"offload_{tag}_loss": round(loss, 3)}
            tot = {k: v - base.get(k, 0)
                   for k, v in dict(getattr(eng,
                                            "_offload_totals",
                                            {})).items()}
            if tot.get("bytes_h2d"):
                res[f"offload_{tag}_stall_fraction"] = round(
                    tot.get("prefetch_stall_s", 0.0) / dt, 4)
                res[f"offload_{tag}_h2d_gb"] = round(
                    tot["bytes_h2d"] / 2**30, 3)
                res[f"offload_{tag}_d2h_gb"] = round(
                    tot["bytes_d2h"] / 2**30, 3)
            del eng
            gc.collect()
            return res
        return thunk

    onchip_zero = {"stage": 3, "schedule": dict(sched)}
    host_zero = {"stage": 3, "schedule": dict(sched),
                 "offload_optimizer": {"device": "cpu"},
                 "offload_param": {"device": "cpu"}}
    out = _ladder([("explicit", run("onchip", onchip_zero))], out,
                  "offload_onchip")
    out = _ladder([("host_dram", run("host", host_zero))], out,
                  "offload_host")
    nvme_path = os.environ.get("DS_BENCH_OFFLOAD_NVME")
    if nvme_path:
        nvme_zero = {"stage": 3, "schedule": dict(sched),
                     "offload_optimizer": {"device": "cpu"},
                     "offload_param": {"device": "nvme",
                                       "nvme_path": nvme_path}}
        out = _ladder([("nvme", run("nvme", nvme_zero))], out,
                      "offload_nvme")
    if "offload_onchip_step_ms" in out and "offload_host_step_ms" in out:
        out["offload_host_vs_onchip"] = round(
            out["offload_onchip_step_ms"] / out["offload_host_step_ms"],
            4)

    # --- synthetic beyond-HBM rung ------------------------------------
    try:
        hbm = (jax.devices()[0].memory_stats() or {}).get("bytes_limit")
    except Exception:  # noqa: BLE001 - backends without memory_stats
        hbm = None
    ratio = float(os.environ.get("DS_BENCH_OFFLOAD_RATIO", "4"))
    if hbm:
        target = ratio * hbm
    else:
        target = float(os.environ.get(
            "DS_BENCH_OFFLOAD_SYNTH_GB", "0.5")) * 2**30
    H, V = 2048, cfg.vocab_size
    itemsize = 2   # bf16 compute rows are what rest in DRAM
    L = max(2, int(-(-(target / itemsize - V * H) // (12 * H * H))))
    synth_cfg = GPTNeoXConfig(vocab_size=V, hidden_size=H,
                              num_layers=L, num_heads=16,
                              max_seq_len=256)
    synth_seq = min(256, seq)
    sbs = max(n_chips, int(os.environ.get("DS_BENCH_OFFLOAD_SYNTH_BS",
                                          str(n_chips))))
    synth_model = GPTNeoX(synth_cfg, use_pallas=True)
    cpu0 = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu0):
        # init on HOST: the whole point is that this model does not fit
        # HBM — params flow host-init -> row store, never full on chip
        synth_params = synth_model.init_params(jax.random.PRNGKey(0))
    synth_bytes = synth_cfg.num_params() * itemsize
    out["offload_synth_params_m"] = round(synth_cfg.num_params() / 1e6, 1)
    out["offload_synth_hbm_ratio"] = (
        round(synth_bytes / hbm, 2) if hbm else None)
    stoks = rng.integers(0, V, size=(1, sbs, synth_seq), dtype=np.int32)

    def synth_done(res):
        # extrapolate the on-chip row to the synthetic shape by the
        # flops ratio (same schedule, same per-flop speed assumption)
        if "offload_onchip_step_ms" in out:
            base = out["offload_onchip_step_ms"]
            scale = ((_flops_per_token(synth_cfg, synth_seq)
                      * sbs * synth_seq)
                     / (_flops_per_token(cfg, seq) * batch * seq))
            extrapolated = base * scale
            res["offload_synth_extrapolated_onchip_ms"] = round(
                extrapolated, 1)
            res["offload_synth_overlap_fraction"] = round(
                extrapolated / res["offload_synth_step_ms"], 4)
        return res

    out = _ladder([("synth_host_dram", lambda: synth_done(run(
        "synth", host_zero, mdl=synth_model, prm=synth_params,
        toks=stoks, n_steps=2, warmup=1, bsize=sbs)()))], out,
        "offload_synth")
    return out


def row_quant():
    """Low-precision row (opt-in DS_BENCH_QUANT=1; docs/quantization.md).
    Three measurements on the headline 125M shape:

    (a) bf16 vs int8-WEIGHT decode: a fixed decode-heavy serve stream
        run at both weight precisions — decode tokens/s and p50
        inter-token. Decode is weight-bandwidth bound, so the ≥1.5×
        acceptance gate applies ON TPU (the Pallas dequant-in-kernel
        path); CPU hosts record the row through the XLA fallback, where
        the ratio is informational only.
    (b) int8-KV capacity: resident sessions at a FIXED pool byte budget
        (DS_BENCH_QUANT_POOL_MB) for bf16 vs int8 pools — the ≥1.9×
        gate is pure accounting (per-page scale pools included).
    (c) compressed vs dense cross-host DP gradients: explicit ZeRO-3
        step time with and without the error-feedback sign-compressed
        reduce-scatter (quantization.gradient_compression).

    Knobs ride in extra; DS_BENCH_QUANT_* envs override the defaults.
    """
    jax = _setup_jax()
    n_chips = len(jax.devices())
    cfg, model, params = _headline_setup(jax)
    out = {}

    max_new = int(os.environ.get("DS_BENCH_QUANT_NEW", "48"))
    n_req = int(os.environ.get("DS_BENCH_QUANT_REQUESTS", "16"))
    prompt_len = int(os.environ.get("DS_BENCH_QUANT_PROMPT", "62"))

    def serve(tag, weight_quant, kv_dtype=None):
        def thunk():
            from deeperspeed_tpu.inference import InferenceEngine
            conf = {"inference": {
                "enabled": True, "page_size": 64, "num_pages": 257,
                "max_batch_size": 16, "token_budget": 2048,
                "prefill_batch_sizes": [4],
                "prefill_lengths": [64],
                "decode_batch_sizes": [16]}}
            if kv_dtype:
                conf["inference"]["kv_cache_dtype"] = kv_dtype
            if weight_quant:
                conf["quantization"] = {"weights": weight_quant}
            eng = InferenceEngine(model, config=conf, params=params)
            rng = np.random.default_rng(0)
            prompts = [list(rng.integers(1, cfg.vocab_size,
                                         size=prompt_len))
                       for _ in range(n_req)]
            # warm both programs, then measure a decode-heavy stream
            eng.generate([prompts[0]], max_new_tokens=2)
            warm = dict(eng.stats)
            itl = []
            last = {}
            t0 = time.perf_counter()
            for p in prompts:
                eng.submit(p, max_new_tokens=max_new)
            while eng.scheduler.has_work:
                eng.step()
                now = time.perf_counter()
                for r in eng.scheduler.running:
                    k = len(r.generated)
                    if last.get(r.request_id, (0, 0))[0] < k:
                        prev = last.get(r.request_id)
                        if prev is not None:
                            itl.append(now - prev[1])
                        last[r.request_id] = (k, now)
            dt = time.perf_counter() - t0
            dtok = eng.stats["decode_tokens"] - warm["decode_tokens"]
            dsec = eng.stats["decode_s"] - warm["decode_s"]
            return {
                f"quant_decode_tok_s_{tag}": round(dtok / max(dsec,
                                                              1e-9), 1),
                f"quant_stream_tok_s_{tag}": round(dtok / dt, 1),
                f"quant_p50_token_ms_{tag}": (
                    round(float(np.percentile(itl, 50)) * 1e3, 2)
                    if itl else None),
                f"quant_weight_dtype_{tag}": eng.dtypes["weight"],
                f"quant_kv_dtype_{tag}": eng.dtypes["kv_cache"],
            }
        return thunk

    # three rungs, one axis at a time: the ≥1.5× weight gate must
    # measure WEIGHTS alone (int8 KV changes attention numerics and
    # adds quantize/dequantize work — conflating them makes the ratio
    # unattributable); the combined rung records the deployment config
    out = _ladder([("bf16", serve("bf16", None))], out, "quant_bf16")
    gc.collect()
    out = _ladder([("int8w", serve("int8w", "int8"))], out, "quant_int8w")
    gc.collect()
    out = _ladder([("int8w_int8kv", serve("int8w_int8kv", "int8",
                                          "int8"))],
                  out, "quant_int8w_int8kv")
    gc.collect()
    a, b = (out.get("quant_decode_tok_s_int8w"),
            out.get("quant_decode_tok_s_bf16"))
    if a and b:
        out["quant_int8_weight_decode_speedup"] = round(a / b, 3)

    # (b) int8-KV resident-session capacity at fixed pool bytes —
    # accounting over the real cache geometry (scale pools included)
    def kv_capacity():
        def thunk():
            from deeperspeed_tpu.inference.kv_cache import PagedKVCache
            import jax.numpy as jnp
            pool_mb = int(os.environ.get("DS_BENCH_QUANT_POOL_MB", "1024"))
            sess_tokens = int(os.environ.get("DS_BENCH_QUANT_SESSION_TOK",
                                             "1024"))
            res = {}
            for tag, dt_ in (("bf16", jnp.bfloat16), ("int8", jnp.int8)):
                c = PagedKVCache(num_layers=cfg.num_layers, num_pages=2,
                                 num_heads=cfg.num_heads, page_size=64,
                                 head_dim=cfg.head_dim, dtype=dt_)
                sessions = (pool_mb << 20) // (c.bytes_per_token()
                                               * sess_tokens)
                res[f"quant_kv_sessions_{tag}"] = int(sessions)
                res[f"quant_kv_bytes_per_token_{tag}"] = \
                    c.bytes_per_token()
            res["quant_kv_capacity_ratio"] = round(
                res["quant_kv_sessions_int8"] /
                max(res["quant_kv_sessions_bf16"], 1), 3)
            res["quant_kv_pool_mb"] = pool_mb
            res["quant_kv_session_tokens"] = sess_tokens
            return res
        return thunk

    out = _ladder([("acct", kv_capacity())], out, "quant_kv")

    # (c) compressed vs dense DP-grad step time on the explicit schedule
    seq = min(int(os.environ.get("DS_BENCH_QUANT_SEQ", "256")),
              cfg.max_seq_len)
    bs = int(os.environ.get("DS_BENCH_QUANT_BS", "4"))
    steps = int(os.environ.get("DS_BENCH_QUANT_STEPS", "6"))

    def grads(tag, compress):
        def thunk():
            batch = bs * n_chips
            rng = np.random.default_rng(0)
            tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                                  dtype=np.int32)
            zero_cfg = {"stage": 3,
                        "stage3_param_persistence_threshold": 0,
                        "schedule": {"mode": "explicit"}}
            extra_cfg = {}
            if compress:
                extra_cfg["quantization"] = {
                    "gradient_compression": {"enabled": True}}
            eng = _neox_engine(model, params, batch, zero_cfg, extra_cfg)
            dt, _ = timed_steps(eng, (tokens, tokens), steps=steps,
                                warmup=2)
            return {f"quant_grad_step_ms_{tag}": round(
                dt / steps * 1e3, 1)}
        return thunk

    out = _ladder([("dense", grads("dense", False))], out, "quant_gdense")
    gc.collect()
    if n_chips > 1:
        out = _ladder([("compressed", grads("compressed", True))], out,
                      "quant_gcomp")
    else:
        # a 1-chip dp world has no gather to compress (every leaf rests
        # replicated) — record the skip instead of a misleading error
        out["quant_gcomp_skipped"] = "single-chip dp world: no " \
            "cross-host gradient collective to compress"
    a, b = (out.get("quant_grad_step_ms_dense"),
            out.get("quant_grad_step_ms_compressed"))
    if a and b:
        out["quant_grad_compress_speedup"] = round(a / b, 3)
    out["quant_knobs"] = {
        "max_new": max_new, "requests": n_req, "prompt": prompt_len,
        "seq": seq, "bs": bs, "steps": steps}
    return out


def row_rl():
    """Online-RL row (docs/rl.md): the co-located train+serve loop on a
    CPU-proxy NeoX. Measures rollout throughput under the
    continuous-batching scheduler, the PPO update step, train->serve
    hot-swap latency, the zero-recompile pin (compile delta across the
    timed iterations must be 0), and the co-residency tax — the SAME
    pretraining step timed alone vs with the RL engine pair (train
    engine + serving engine + its KV pool) resident; the acceptance
    target is <=10% degradation. Scale with DS_BENCH_RL_{HIDDEN,
    LAYERS,BS,ITERS,...}; opt-in via DS_BENCH_RL=1."""
    jax = _setup_jax()
    import deeperspeed_tpu
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig
    from deeperspeed_tpu.rl import RLDriver

    n_chips = len(jax.devices())
    hidden = int(os.environ.get("DS_BENCH_RL_HIDDEN", "256"))
    layers = int(os.environ.get("DS_BENCH_RL_LAYERS", "4"))
    heads = int(os.environ.get("DS_BENCH_RL_HEADS", "8"))
    vocab = int(os.environ.get("DS_BENCH_RL_VOCAB", "8192"))
    bs = int(os.environ.get("DS_BENCH_RL_BS", "8"))    # rollouts / chip
    iters = int(os.environ.get("DS_BENCH_RL_ITERS", "4"))
    steps = int(os.environ.get("DS_BENCH_RL_STEPS", "6"))
    max_new = int(os.environ.get("DS_BENCH_RL_MAX_NEW", "16"))
    prompt_len = int(os.environ.get("DS_BENCH_RL_PROMPT", "32"))

    bs += bs % 2                       # group_size-2 pairing
    rollouts = bs * n_chips
    seq = -(-(prompt_len + max_new) // 8) * 8

    cfg = GPTNeoXConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_seq_len=max(seq, 128))
    model = GPTNeoX(cfg, use_pallas=False)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    lm_tokens = rng.integers(0, vocab, size=(1, rollouts, seq),
                             dtype=np.int32)
    out = {}

    def train_engine(extra_cfg=None):
        config = {"train_batch_size": rollouts,
                  "gradient_accumulation_steps": 1,
                  "steps_per_print": 10_000,
                  "optimizer": {"type": "Adam", "params": {"lr": 1e-4}}}
        config.update(extra_cfg or {})
        eng, *_ = deeperspeed_tpu.initialize(
            model=model, model_parameters=params, config_params=config)
        return eng

    def run():
        # (a) pure-pretraining baseline: the degradation denominator,
        # measured BEFORE the RL pair exists
        base = train_engine()
        dt, _ = timed_steps(base, (lm_tokens, lm_tokens), steps=steps,
                            warmup=2)
        pre_ms = dt / steps * 1e3

        # (b) the RL loop: warmup iteration compiles every path (serve
        # buckets, eval logits, PPO update), then the timed iterations
        # must hold the zero-recompile pin
        rl_engine = train_engine({"rl": {
            "enabled": True, "loss": "ppo_clip",
            "rollouts_per_iteration": rollouts, "group_size": 2,
            "max_new_tokens": max_new, "sequence_length": seq}})
        pages_per = -(-seq // 16)
        serve_config = {"inference": {
            "enabled": True, "page_size": 16,
            "num_pages": 2 * rollouts * pages_per,
            "max_batch_size": min(rollouts, 8),
            "token_budget": max(2 * rollouts * seq, 512),
            "prefill_lengths": [-(-prompt_len // 16) * 16],
            "prefill_batch_sizes": [1, 2, 4],
            "decode_batch_sizes": [1, 2, 4, 8],
            "temperature": 1.0, "seed": 7}}
        prompts = [list(map(int,
                            rng.integers(1, vocab, size=prompt_len)))
                   for _ in range(max(rollouts // 2, 4))]
        driver = RLDriver(rl_engine, prompts,
                          lambda pr, resp: float(len(set(resp))),
                          serve_config)
        driver.run_iteration()
        t0 = time.perf_counter()
        rows = [driver.run_iteration() for _ in range(iters)]
        wall = time.perf_counter() - t0

        roll_s = sum(r["rollout_s"] for r in rows)
        roll_tok = sum(r["rollout_tokens"] for r in rows)
        res = {
            "rl_rollout_tokens_per_s": round(
                roll_tok / max(roll_s, 1e-9), 1),
            # everything in the iteration that is not rollout: behavior/
            # reference logprobs, batch build, the PPO update, the swap
            "rl_update_step_ms": round((wall - roll_s) / iters * 1e3, 1),
            "rl_swap_ms": round(
                sum(r["swap_ms"] for r in rows) / iters, 2),
            "rl_compile_delta": sum(r["compile_delta"] for r in rows),
            "rl_mean_kl": round(rows[-1]["mean_kl"], 5),
        }

        # (c) co-residency tax: the SAME pretraining step, RL pair now
        # resident (no recompile — same engine, same shapes)
        dt2, _ = timed_steps(base, (lm_tokens, lm_tokens), steps=steps,
                             warmup=1)
        co_ms = dt2 / steps * 1e3
        res["rl_pretrain_step_ms"] = round(pre_ms, 1)
        res["rl_colocated_step_ms"] = round(co_ms, 1)
        res["rl_train_step_degradation"] = round(co_ms / pre_ms - 1, 4)
        return res

    out = _ladder([("ppo", run)], out, "rl")
    out["rl_knobs"] = {
        "hidden": hidden, "layers": layers, "rollouts": rollouts,
        "seq": seq, "max_new": max_new, "prompt": prompt_len,
        "iters": iters, "steps": steps}
    return out


def row_multislice():
    """Two-slice DCN drill (opt-in via DS_BENCH_MULTISLICE=1), on a
    CPU-drivable NeoX proxy so the row runs on a single host exactly
    like the fleet regime it models. Two measurements:

    (a) throughput under injected cross-slice latency: the 4-stage 1F1B
    pipeline split 2x2 across a simulated DCN boundary, with the
    `dcn_delay` fault charging DS_BENCH_MS_DELAY_MS per EXPOSED
    crossing every step, on the classic wire (2*n_micro exposed hops)
    and the comm-overlap wire (fill+drain only). Reported as the
    tokens/s ratio vs the same engine run single-slice — the overlap
    wire is the one expected to hold the <=10%-loss bar.

    (b) slice loss: a scripted slice_kill, heartbeat detection,
    emergency checkpoint, in-process `repartition_after_slice_loss` to
    the surviving 2-stage pipeline — MTTR seconds from detection to
    the first surviving optimizer step, with zero survivor restarts by
    construction, plus the loss-trajectory alignment bool vs an
    unfaulted reference engine resumed from the same checkpoint."""
    import copy
    import shutil
    import tempfile

    jax = _setup_jax()
    import deeperspeed_tpu
    from deeperspeed_tpu.elasticity import (SliceLostError,
                                            repartition_after_slice_loss)
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig

    n_chips = len(jax.devices())
    stages = int(os.environ.get("DS_BENCH_MS_STAGES", "4"))
    n_micro = int(os.environ.get("DS_BENCH_MS_MICRO", "8"))
    delay_s = float(os.environ.get("DS_BENCH_MS_DELAY_MS", "1.0")) / 1e3
    seq = int(os.environ.get("DS_BENCH_MS_SEQ", "256"))
    steps = int(os.environ.get("DS_BENCH_MS_STEPS", "8"))
    hidden = int(os.environ.get("DS_BENCH_MS_HIDDEN", "512"))
    if n_chips % stages:
        return {"multislice_error":
                f"stages={stages} does not divide chips={n_chips}"}
    dp = n_chips // stages
    bs = 2 * n_micro * dp
    cfg = GPTNeoXConfig(vocab_size=8192, hidden_size=hidden,
                        num_layers=2 * stages,
                        num_heads=max(hidden // 64, 2),
                        max_seq_len=seq)
    model = GPTNeoX(cfg, use_pallas=False)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, bs, seq),
                          dtype=np.int32)
    batch = (tokens, tokens)

    def conf(overlap=False, multislice=False, faults=None, ckpt=None):
        c = {"train_batch_size": bs,
             "steps_per_print": 10_000,
             "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
             "pipeline": {"stages": stages, "micro_batches": n_micro,
                          "comm_overlap": overlap}}
        if multislice:
            c["multislice"] = {"slices": 2, "names": ["s0", "s1"]}
        if faults is not None:
            c["multislice"]["slice_peers"] = {"s0": ["hostA"],
                                              "s1": ["hostB"]}
            c["elasticity"] = {"heartbeat": {
                "enabled": True, "interval_s": 0.05,
                "warn_after_s": 0.15, "fail_after_s": 0.3}}
            c["training_health"] = {"fault_injection": {"faults": faults}}
        if ckpt is not None:
            c["checkpoint"] = {"save_dir": ckpt, "async_save": False}
        return c

    def engine(c):
        eng, *_ = deeperspeed_tpu.initialize(
            model=model, model_parameters=params, config_params=c)
        return eng

    out = {"multislice_dcn_delay_ms": delay_s * 1e3,
           "multislice_n_micro": n_micro, "multislice_stages": stages}

    def wire_race():
        base = engine(conf())
        dt_base, _ = timed_steps(base, batch, steps=steps, warmup=3)
        out["multislice_single_slice_tokens_per_sec"] = round(
            bs * seq * steps / dt_base, 1)
        del base
        gc.collect()
        for overlap, tag in ((False, "classic"), (True, "overlap")):
            # a far-future dcn_delay entry arms the injector; the
            # per-step charge below drives the REAL stall path the
            # fault kind uses, at `delay_s` per exposed crossing
            eng = engine(conf(overlap=overlap, multislice=True,
                              faults=[{"kind": "dcn_delay",
                                       "step": 10 ** 9,
                                       "seconds": delay_s}]))
            exposed = eng._multislice.exposed_crossings(
                n_micro, 2 if overlap else 1)
            for _ in range(3):
                eng.train_batch(batch=batch)
            force(eng.state.params)
            t0 = time.perf_counter()
            for _ in range(steps):
                eng._apply_host_fault({"kind": "dcn_delay",
                                       "seconds": delay_s})
                eng.train_batch(batch=batch)
            force(eng.state.params)
            dt = time.perf_counter() - t0
            out[f"multislice_{tag}_exposed_crossings"] = exposed
            out[f"multislice_{tag}_tput_ratio"] = round(dt_base / dt, 4)
            del eng
            gc.collect()
        return {}

    def chaos():
        workdir = tempfile.mkdtemp(prefix="ds_bench_ms_")
        eng = None
        recovered = None
        reference = None
        try:
            eng = engine(conf(multislice=True, ckpt=workdir,
                              faults=[{"kind": "slice_kill", "step": 3,
                                       "slice": "s1"}]))
            err = None
            try:
                for _ in range(200):
                    eng.train_batch(batch=batch)
                    time.sleep(0.02)
            except SliceLostError as e:
                err = e
            if err is None:
                return {"multislice_chaos_error":
                        "slice_kill never escalated"}
            drill_conf = conf(multislice=True, ckpt=workdir,
                              faults=[{"kind": "slice_kill", "step": 3,
                                       "slice": "s1"}])
            recovered, surv = repartition_after_slice_loss(
                err, drill_conf,
                lambda c: GPTNeoX(cfg, use_pallas=False), workdir)
            recovered.train_batch(batch=batch)
            force(recovered.state.params)
            mttr = time.monotonic() - err.detected_at
            ref_model = GPTNeoX(cfg, use_pallas=False)
            reference, *_ = deeperspeed_tpu.initialize(
                model=ref_model, config_params=copy.deepcopy(surv))
            reference.load_checkpoint(workdir)
            reference.train_batch(batch=batch)
            rec_l = float(recovered.train_batch(batch=batch))
            ref_l = float(reference.train_batch(batch=batch))
            return {
                "multislice_slice_kill_mttr_s": round(mttr, 2),
                "multislice_survivor_stages": surv["pipeline"]["stages"],
                "multislice_survivor_restarts": 0,
                "multislice_trajectory_aligned": bool(
                    abs(rec_l - ref_l) <= 1e-5 * max(abs(ref_l), 1.0)),
            }
        finally:
            for e in (eng, recovered, reference):
                if e is not None and \
                        getattr(e, "peer_monitor", None) is not None:
                    e.peer_monitor.stop()
            shutil.rmtree(workdir, ignore_errors=True)
            gc.collect()

    _ladder([("wire", wire_race)], out, "multislice_wire")
    _ladder([("chaos", chaos)], out, "multislice_chaos")
    return out


ROW_FNS = {"zero3": row_zero3, "bert128": row_bert128,
           "bert512": row_bert512, "gpt2xl": row_gpt2xl,
           "longseq": row_longseq, "moe": row_moe, "ckpt": row_ckpt,
           "sentinel": row_sentinel, "telemetry": row_telemetry,
           "packed": row_packed, "serve": row_serve,
           "serve_chaos": row_serve_chaos,
           "serve_prefix": row_serve_prefix,
           "serve_disagg": row_serve_disagg,
           "elastic": row_elastic, "fleet": row_fleet,
           "pipe": row_pipe, "offload": row_offload,
           "quant": row_quant, "plan": row_plan, "rl": row_rl,
           "multislice": row_multislice}


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def rows_enabled():
    sel = os.environ.get("DS_BENCH_ROWS", "all")
    order = list(ROW_ORDER)
    # checkpoint-stall row is opt-in (DS_BENCH_CKPT=1 or an explicit
    # DS_BENCH_ROWS pick): each save writes ~1.5 GB to local disk
    if os.environ.get("DS_BENCH_CKPT", "0") not in ("0", "", "false"):
        order.append("ckpt")
    if os.environ.get("DS_BENCH_SENTINEL", "0") not in ("0", "", "false"):
        order.append("sentinel")
    if os.environ.get("DS_BENCH_TELEMETRY", "0") not in ("0", "", "false"):
        order.append("telemetry")
    if os.environ.get("DS_BENCH_PACKED", "0") not in ("0", "", "false"):
        order.append("packed")
    if os.environ.get("DS_BENCH_SERVE", "0") not in ("0", "", "false"):
        order.append("serve")
    if os.environ.get("DS_BENCH_SERVE_CHAOS", "0") not in \
            ("0", "", "false"):
        order.append("serve_chaos")
    if os.environ.get("DS_BENCH_SERVE_PREFIX", "0") not in \
            ("0", "", "false"):
        order.append("serve_prefix")
    if os.environ.get("DS_BENCH_SERVE_DISAGG", "0") not in \
            ("0", "", "false"):
        order.append("serve_disagg")
    if os.environ.get("DS_BENCH_ELASTIC", "0") not in ("0", "", "false"):
        order.append("elastic")
    if os.environ.get("DS_BENCH_FLEET", "0") not in ("0", "", "false"):
        order.append("fleet")
    if os.environ.get("DS_BENCH_PIPE", "0") not in ("0", "", "false"):
        order.append("pipe")
    if os.environ.get("DS_BENCH_OFFLOAD", "0") not in ("0", "", "false"):
        order.append("offload")
    if os.environ.get("DS_BENCH_QUANT", "0") not in ("0", "", "false"):
        order.append("quant")
    if os.environ.get("DS_BENCH_PLAN", "0") not in ("0", "", "false"):
        order.append("plan")
    if os.environ.get("DS_BENCH_RL", "0") not in ("0", "", "false"):
        order.append("rl")
    if os.environ.get("DS_BENCH_MULTISLICE", "0") not in \
            ("0", "", "false"):
        order.append("multislice")
    if sel in ("all", ""):
        return order
    if sel == "none":               # headline only (perf iteration)
        return []
    picked = {r.strip() for r in sel.split(",")}
    if "bert" in picked:            # back-compat alias
        picked |= {"bert128", "bert512"}
    for opt_in in ("ckpt", "sentinel", "telemetry", "packed", "serve",
                   "serve_chaos", "serve_prefix", "serve_disagg",
                   "elastic", "fleet",
                   "pipe", "offload", "quant", "plan", "rl",
                   "multislice"):
        if opt_in in picked and opt_in not in order:
            order.append(opt_in)
    return [r for r in order if r in picked]


def run_row_subprocess(name, extra):
    """One row in its own process: OOMs/compiler crashes stay contained,
    HBM is fully released afterwards. One retry for transient (infra)
    failures."""
    timeout = ROW_TIMEOUT.get(name, ROW_TIMEOUT_DEFAULT)
    cmd = [sys.executable, os.path.abspath(__file__), "--row", name]
    last_err = ""
    for attempt in range(2):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout, env=os.environ.copy())
        except subprocess.TimeoutExpired:
            last_err = f"row timed out after {timeout}s"
            # the killed child's HBM release lags the SIGKILL; an
            # immediate retry OOMs against its zombie buffers (observed:
            # a timed-out gpt2xl attempt poisoned all retry rungs)
            time.sleep(30)
            continue
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    extra.update(json.loads(line))
                    return
                except json.JSONDecodeError:
                    break
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        last_err = (f"rc={proc.returncode}: " +
                    " | ".join(tail[-3:]))[:300]
    extra[f"{name}_row_error"] = last_err


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--row":
        print(json.dumps(ROW_FNS[sys.argv[2]]()))
        return 0

    jax = _setup_jax()
    import deeperspeed_tpu  # noqa: F401 - fail fast if the package is broken

    devices = jax.devices()
    n_chips = len(devices)
    peak = peak_flops_per_chip(devices[0])

    # ------------------------------------------------------------------
    # headline: GPT-NeoX-125M ZeRO-2, seq 1024 (measured in-process)
    # ------------------------------------------------------------------
    cfg, model, params = _headline_setup(jax)
    seq = 1024
    # bs48 fits the 16GB chip with the single-block attention kernels and
    # runs ~1.5% higher MFU than bs32 (bs64 OOMs); override via env.
    batch_per_chip = int(os.environ.get("DS_BENCH_BS", "48"))
    batch = batch_per_chip * n_chips

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                          dtype=np.int32)
    stacked = (tokens, tokens)

    engine = _neox_engine(model, params, batch, {"stage": 2})
    steps = int(os.environ.get("DS_BENCH_STEPS", "15"))
    elapsed, final_loss = timed_steps(engine, stacked, steps=steps,
                                      warmup=3)
    tokens_per_sec_chip = batch * seq * steps / elapsed / n_chips

    flops_per_token = _flops_per_token(cfg, seq)
    achieved = tokens_per_sec_chip * flops_per_token
    mfu = achieved / peak

    del engine
    gc.collect()

    extra = {
        "chips": n_chips,
        "device": str(devices[0]),
        "mfu": round(mfu, 4),
        "achieved_tflops_per_chip": round(achieved / 1e12, 2),
        "params_m": round(cfg.num_params() / 1e6, 1),
        "final_loss": final_loss,
        "seq": seq,
        "batch_per_chip": batch_per_chip,
    }

    # Host-offload needs a local chip link (a tunneled chip turns the
    # per-step host round-trip into minutes); opt in via env.
    if os.environ.get("DS_BENCH_OFFLOAD", "0") not in ("0", "", "false"):
        try:
            eng = _neox_engine(model, params, batch,
                               {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}})
            dt, _ = timed_steps(eng, stacked, steps=2, warmup=1)
            tps = batch * seq * 2 / dt / n_chips
            extra["zero2_offload_tokens_per_sec_chip"] = round(tps, 1)
            extra["zero2_offload_mfu"] = round(
                tps * flops_per_token / peak, 4)
            del eng
            gc.collect()
        except Exception as e:  # noqa: BLE001
            extra["offload_error"] = f"{type(e).__name__}: {e}"[:200]

    del model, params
    gc.collect()
    # release parent-held device buffers/programs before the row
    # subprocesses: HBM is shared with them even where the backend
    # multiplexes clients (the axon tunnel does; on an exclusive-TPU
    # deployment run rows via separate DS_BENCH_ROWS invocations)
    jax.clear_caches()

    def emit():
        print(json.dumps({
            "metric": "gpt_neox_125m_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec_chip, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": round(mfu / 0.40, 4),
            "extra": extra,
        }), flush=True)

    # The headline is measured; never lose it to a driver time budget or
    # a row-spawn failure — emit() runs on EVERY exit path, marking the
    # row that was cut.
    def _bail(signum, frame):  # noqa: ARG001
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _bail)
    try:
        for name in rows_enabled():
            try:
                run_row_subprocess(name, extra)
            except KeyboardInterrupt:
                extra[f"{name}_row_error"] = "interrupted (time budget)"
                extra["rows_interrupted"] = name
                break
            except Exception as e:  # noqa: BLE001 - spawn failures etc.
                extra[f"{name}_row_error"] = \
                    f"{type(e).__name__}: {e}"[:200]
    finally:
        emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
