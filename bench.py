"""Benchmark: training throughput on the attached TPU chip(s).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric is tokens/sec/chip for a bf16 GPT-NeoX-125M training step
(ZeRO-2); ``vs_baseline`` is MFU / 0.40 — the BASELINE.md north-star is
≥40% MFU, so ≥1.0 means target hit.

``extra`` carries the round-4 config ladder (each row tokens/s/chip +
MFU, short windows). DS_BENCH_ROWS selects a comma list of row KEYS
(default all); rows never fail the headline — errors report inline:
  - zero3    (GPT-NeoX-125M, ZeRO-3)
  - bert     (bert_large_seq128/seq512: masked + fused in-kernel attn
              dropout — the reference's flagship single-device workload,
              docs/_tutorials/bert-pretraining.md)
  - gpt2xl   (gpt2_xl_1p5b: Megatron-GPT2 48L/1600H ladder rung, ZeRO-3
              + CPU-offload tiers + peak RSS; reference
              tests/model/Megatron_GPT2)
  - longseq  (longseq_16k: 16k-token causal flash row)
  - moe      (moe_top2: GShard top-2 MoE row)
"""

import gc
import json
import os
import resource
import sys
import time

import numpy as np


def peak_flops_per_chip(device):
    """bf16 peak TFLOPS by TPU generation (public spec sheet numbers)."""
    kind = getattr(device, "device_kind", "") or str(device)
    kind = kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6": 918e12, "v6e": 918e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # conservative default


def force(tree):
    """Materialize on host: `block_until_ready` alone is not a reliable
    fence on tunneled/remote backends — an actual transfer is."""
    import jax
    jax.block_until_ready(tree)
    return np.asarray(jax.tree_util.tree_leaves(tree)[0])


def timed_steps(engine, batch, steps, warmup):
    for _ in range(warmup):
        loss = engine.train_batch(batch=batch)
    force(engine.state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    force(engine.state.params)
    return time.perf_counter() - t0, float(loss)


def rows_enabled():
    sel = os.environ.get("DS_BENCH_ROWS", "all")
    if sel in ("all", ""):
        return None
    return {r.strip() for r in sel.split(",")}


def main():
    import jax

    import deeperspeed_tpu
    from deeperspeed_tpu.models.gpt_neox import GPTNeoX, GPTNeoXConfig

    devices = jax.devices()
    n_chips = len(devices)
    peak = peak_flops_per_chip(devices[0])
    only = rows_enabled()

    def row_on(name):
        return only is None or name in only

    # ------------------------------------------------------------------
    # headline: GPT-NeoX-125M ZeRO-2, seq 1024
    # ------------------------------------------------------------------
    cfg = GPTNeoXConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024)
    seq = 1024
    # bs48 fits the 16GB chip with the single-block attention kernels and
    # runs ~1.5% higher MFU than bs32 (bs64 OOMs); override via env.
    batch_per_chip = int(os.environ.get("DS_BENCH_BS", "48"))
    batch = batch_per_chip * n_chips

    model = GPTNeoX(cfg, use_pallas=True)
    params = model.init_params(jax.random.PRNGKey(0))

    def neox_engine(zero_cfg):
        eng, *_ = deeperspeed_tpu.initialize(
            model=model,
            model_parameters=params,
            config_params={
                "train_batch_size": batch,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 10_000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "fp16": {"enabled": True, "type": "bfloat16"},
                "zero_optimization": zero_cfg,
            })
        return eng

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, batch, seq),
                          dtype=np.int32)
    stacked = (tokens, tokens)

    engine = neox_engine({"stage": 2})
    elapsed, final_loss = timed_steps(engine, stacked, steps=10, warmup=3)
    tokens_per_sec_chip = batch * seq * 10 / elapsed / n_chips

    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * \
        cfg.hidden_size * seq
    achieved = tokens_per_sec_chip * flops_per_token
    mfu = achieved / peak

    del engine
    gc.collect()

    extra = {
        "chips": n_chips,
        "device": str(devices[0]),
        "mfu": round(mfu, 4),
        "achieved_tflops_per_chip": round(achieved / 1e12, 2),
        "params_m": round(n_params / 1e6, 1),
        "final_loss": final_loss,
        "seq": seq,
        "batch_per_chip": batch_per_chip,
    }

    # ------------------------------------------------------------------
    # zero3 row (same model; equal methodology as round 2/3)
    # ------------------------------------------------------------------
    if row_on("zero3"):
        try:
            eng = neox_engine({"stage": 3})
            dt, _ = timed_steps(eng, stacked, steps=8, warmup=4)
            tps = batch * seq * 8 / dt / n_chips
            extra["zero3_tokens_per_sec_chip"] = round(tps, 1)
            extra["zero3_mfu"] = round(tps * flops_per_token / peak, 4)
            del eng
            gc.collect()
        except Exception as e:  # noqa: BLE001 - report, don't fail
            extra["zero3_error"] = f"{type(e).__name__}: {e}"[:200]

    # Host-offload needs a local chip link (a tunneled chip turns the
    # per-step host round-trip into minutes); opt in via env.
    if os.environ.get("DS_BENCH_OFFLOAD", "0") not in ("0", "", "false"):
        try:
            eng = neox_engine({"stage": 2,
                               "offload_optimizer": {"device": "cpu"}})
            dt, _ = timed_steps(eng, stacked, steps=2, warmup=1)
            tps = batch * seq * 2 / dt / n_chips
            extra["zero2_offload_tokens_per_sec_chip"] = round(tps, 1)
            extra["zero2_offload_mfu"] = round(
                tps * flops_per_token / peak, 4)
            del eng
            gc.collect()
        except Exception as e:  # noqa: BLE001
            extra["offload_error"] = f"{type(e).__name__}: {e}"[:200]

    # ------------------------------------------------------------------
    # BERT-Large rows: the reference's flagship single-device benchmark
    # (bert-pretraining tutorial). Masked batches + attention dropout
    # 0.1 → the fused kbias+dropout kernel path, training mode.
    # ------------------------------------------------------------------
    def bert_row(seq_len, bs):
        from deeperspeed_tpu.models.bert import (BertConfig,
                                                 BertForPreTraining)
        bcfg = BertConfig.large(max_position_embeddings=max(512, seq_len))
        bmodel = BertForPreTraining(bcfg)
        bparams = bmodel.init_params(jax.random.PRNGKey(1))
        eng, *_ = deeperspeed_tpu.initialize(
            model=bmodel, model_parameters=bparams,
            config_params={
                "train_batch_size": bs,
                "steps_per_print": 10_000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "fp16": {"enabled": True, "type": "bfloat16"},
                "zero_optimization": {"stage": 2},
            })
        r = np.random.default_rng(2)
        ids = r.integers(0, bcfg.vocab_size, (1, bs, seq_len), np.int32)
        mask = np.ones((1, bs, seq_len), np.float32)
        labels = np.where(r.random((1, bs, seq_len)) < 0.15, ids,
                          -1).astype(np.int32)
        b = {"input_ids": ids,
             "token_type_ids": np.zeros_like(ids),
             "attention_mask": mask,
             "masked_lm_labels": labels,
             "next_sentence_label": r.integers(0, 2, (1, bs), np.int32)}
        steps = 6
        dt, _ = timed_steps(eng, b, steps=steps, warmup=3)
        tps = bs * seq_len * steps / dt / n_chips
        H, L, V = bcfg.hidden_size, bcfg.num_layers, bcfg.vocab_size
        # matmul params: 12H^2/layer (qkv+out+ffn@4H) + MLM transform
        # + tied decoder; attention term 12*L*H*S (qk+pv, fwd+bwd)
        ftok = 6 * (L * 12 * H * H + H * H + H * V) + 12 * L * H * seq_len
        del eng
        gc.collect()
        return round(tps, 1), round(tps * ftok / peak, 4)

    for seq_len, bs_default in ((128, 64), (512, 16)):
        name = f"bert_large_seq{seq_len}"
        if not row_on("bert"):
            continue
        try:
            bs = int(os.environ.get(f"DS_BENCH_BERT_BS{seq_len}",
                                    str(bs_default))) * n_chips
            tps, m = bert_row(seq_len, bs)
            extra[f"{name}_tokens_per_sec_chip"] = tps
            extra[f"{name}_mfu"] = m
        except Exception as e:  # noqa: BLE001
            extra[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]

    # ------------------------------------------------------------------
    # Megatron-GPT2 1.5B rung: 48L/1600H/seq1024 (reference
    # Megatron_GPT2 perf ladder), ZeRO-3 + CPU-offload optimizer tiers.
    # Beyond-HBM optimizer state → host masters + native C++ Adam.
    # ------------------------------------------------------------------
    if row_on("gpt2xl"):
        try:
            from deeperspeed_tpu.models.gpt2 import GPT2, GPT2Config
            xcfg = GPT2Config.megatron_1_5b()
            xmodel = GPT2(xcfg, use_pallas=True, remat_blocks=True)
            xparams = xmodel.init_params(jax.random.PRNGKey(3))
            bs = int(os.environ.get("DS_BENCH_XL_BS", "8")) * n_chips
            eng, *_ = deeperspeed_tpu.initialize(
                model=xmodel, model_parameters=xparams,
                config_params={
                    "train_batch_size": bs,
                    "steps_per_print": 10_000,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "fp16": {"enabled": True, "type": "bfloat16"},
                    "zero_optimization": {
                        "stage": 3,
                        "offload_optimizer": {"device": "cpu"}},
                })
            del xparams
            gc.collect()
            r = np.random.default_rng(4)
            xtok = r.integers(0, xcfg.vocab_size, (1, bs, 1024), np.int32)
            dt, xl_loss = timed_steps(eng, (xtok, xtok), steps=2,
                                      warmup=1)
            tps = bs * 1024 * 2 / dt / n_chips
            xn = xcfg.num_params()
            xftok = 6 * xn + 12 * xcfg.num_layers * xcfg.hidden_size * 1024
            extra["gpt2_xl_1p5b_tokens_per_sec_chip"] = round(tps, 1)
            extra["gpt2_xl_1p5b_mfu"] = round(tps * xftok / peak, 4)
            extra["gpt2_xl_1p5b_params_b"] = round(xn / 1e9, 3)
            extra["gpt2_xl_1p5b_loss"] = xl_loss
            extra["gpt2_xl_1p5b_peak_rss_gb"] = round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss /
                1e6, 2)
            del eng
            gc.collect()
        except Exception as e:  # noqa: BLE001
            extra["gpt2_xl_1p5b_error"] = f"{type(e).__name__}: {e}"[:200]

    # ------------------------------------------------------------------
    # long-context row: 16k causal flash (small vocab so the loss
    # logits don't dominate HBM; this row regression-tracks the
    # attention path, where the long-seq flops live)
    # ------------------------------------------------------------------
    if row_on("longseq"):
        try:
            lcfg = GPTNeoXConfig(vocab_size=8192, hidden_size=768,
                                 num_layers=12, num_heads=12,
                                 max_seq_len=16384)
            lmodel = GPTNeoX(lcfg, use_pallas=True, remat_blocks=True)
            lparams = lmodel.init_params(jax.random.PRNGKey(5))
            lbs = int(os.environ.get("DS_BENCH_LONG_BS", "1")) * n_chips
            eng, *_ = deeperspeed_tpu.initialize(
                model=lmodel, model_parameters=lparams,
                config_params={
                    "train_batch_size": lbs,
                    "steps_per_print": 10_000,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "fp16": {"enabled": True, "type": "bfloat16"},
                    "zero_optimization": {"stage": 2},
                })
            r = np.random.default_rng(6)
            ltok = r.integers(0, lcfg.vocab_size, (1, lbs, 16384),
                              np.int32)
            dt, _ = timed_steps(eng, (ltok, ltok), steps=3, warmup=2)
            tps = lbs * 16384 * 3 / dt / n_chips
            ln = lcfg.num_params()
            lftok = 6 * ln + 12 * lcfg.num_layers * lcfg.hidden_size * \
                16384 // 2   # causal: half the score tiles are dead
            extra["longseq_16k_tokens_per_sec_chip"] = round(tps, 1)
            extra["longseq_16k_mfu"] = round(tps * lftok / peak, 4)
            del eng
            gc.collect()
        except Exception as e:  # noqa: BLE001
            extra["longseq_16k_error"] = f"{type(e).__name__}: {e}"[:200]

    # ------------------------------------------------------------------
    # MoE row: GShard top-2, 8 experts (single chip: dense dispatch;
    # regression-tracks routing + expert compute)
    # ------------------------------------------------------------------
    if row_on("moe"):
        try:
            mcfg = GPTNeoXConfig(vocab_size=50304, hidden_size=768,
                                 num_layers=12, num_heads=12,
                                 max_seq_len=1024, moe_num_experts=8,
                                 moe_top_k=2)
            mmodel = GPTNeoX(mcfg, use_pallas=True)
            mparams = mmodel.init_params(jax.random.PRNGKey(7))
            mbs = int(os.environ.get("DS_BENCH_MOE_BS", "8")) * n_chips
            eng, *_ = deeperspeed_tpu.initialize(
                model=mmodel, model_parameters=mparams,
                config_params={
                    "train_batch_size": mbs,
                    "steps_per_print": 10_000,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "fp16": {"enabled": True, "type": "bfloat16"},
                    "zero_optimization": {"stage": 2},
                })
            r = np.random.default_rng(8)
            mtok = r.integers(0, mcfg.vocab_size, (1, mbs, 1024),
                              np.int32)
            dt, _ = timed_steps(eng, (mtok, mtok), steps=4, warmup=2)
            tps = mbs * 1024 * 4 / dt / n_chips
            # active params/token: top-2 of 8 experts → dense-equivalent
            # flops use 2 expert FFNs per token plus the shared trunk
            H, L = mcfg.hidden_size, mcfg.num_layers
            trunk = L * 4 * H * H + mcfg.vocab_size * H
            expert = L * mcfg.moe_top_k * 8 * H * H
            mftok = 6 * (trunk + expert) + 12 * L * H * 1024
            extra["moe_top2_tokens_per_sec_chip"] = round(tps, 1)
            extra["moe_top2_active_mfu"] = round(tps * mftok / peak, 4)
            del eng
            gc.collect()
        except Exception as e:  # noqa: BLE001
            extra["moe_top2_error"] = f"{type(e).__name__}: {e}"[:200]

    print(json.dumps({
        "metric": "gpt_neox_125m_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": extra,
    }))


if __name__ == "__main__":
    sys.exit(main())
