"""Repo-native developer tooling (not shipped with the package)."""
