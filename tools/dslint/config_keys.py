"""Rule 8: the cross-file parse-only config-key pass.

The PR 9 defect class: a strict block parser accepts and validates a
key, stores the value — and nothing ever reads it. The knob is
documented, type-checked, and silently does nothing (the supervisor
block shipped exactly like this; `a2a_overlap_chunks` sat inert on the
GSPMD path).

Mechanics:

1. **Harvest** — every string-resolvable element of a ``known = {...}``
   / ``*_known`` / ``*_KEYS`` set literal (the unknown-key-rejection
   discipline every strict block parser in this repo follows). Elements
   are string constants or ``c.CONSTANT`` attributes resolved through
   the module's own imports into ``*constants*.py`` assignment tables.
2. **Consume** — a key counts as consumed when its string appears, in
   code *outside* parser functions, as: a Load-context attribute
   (``cfg.prefetch_depth``), a Load-context subscript
   (``params["prefetch_depth"]`` or ``params[c.KEY]``), a
   ``.get("prefetch_depth")``/``.pop`` call, a ``"key" in x``
   membership test, a call keyword argument (``Telemetry(mfu=...)``) or
   a function parameter name (``def __init__(self, mfu=True)`` — how
   ``Thing(**parsed_block)`` consumption manifests), or as a substring
   of a Load-context attribute (derived attributes:
   ``tag_validation`` -> ``self.checkpoint_tag_validation_mode``). A
   parser function is one that performs unknown-key rejection (contains
   a known-set assignment) or is named ``parse_*``/``_parse_*``; reads
   there are the parse itself, not a consumer.
3. **Escape** — a key legitimately read outside the package (the
   launcher re-parses the config JSON; external dashboards read some
   blocks) carries ``# dslint: consumed-by-launcher`` on its known-set
   element line.
"""

import ast
import re

from .resolve import call_name, import_aliases, last_component
from .rules import Rule, register

_KNOWN_SET_NAME = re.compile(r"(^|_)(known|keys)$", re.IGNORECASE)
CONSUMED_ANNOTATION = "consumed-by-launcher"

# Keys every block shares whose consumption is structural (the parser
# itself gates on them); their absence elsewhere is not the PR 9 class.
_STRUCTURAL_KEYS = {"enabled"}


def _constants_tables(sources):
    """{relpath: {CONST_NAME: "string value"}} for *constants*.py files."""
    tables = {}
    for src in sources:
        if "constants" not in src.path.rsplit("/", 1)[-1]:
            continue
        table = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                table[node.targets[0].id] = node.value.value
        if table:
            tables[src.path] = table
    return tables


def _constants_aliases(src, tables):
    """Map import alias -> constants table for this module.

    Any imported module whose last path component contains 'constants'
    is resolved against the harvested tables, preferring the table
    whose path shares the longest suffix with the import."""
    out = {}
    for node in src.nodes():
        mods = []
        if isinstance(node, ast.Import):
            mods = [(a.asname or a.name.split(".")[0], a.name)
                    for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.names:
            mod = node.module or ""
            mods = [(a.asname or a.name, f"{mod}.{a.name}" if mod else a.name)
                    for a in node.names if a.name != "*"]
        for alias, target in mods:
            if "constants" not in last_component(target):
                continue
            # match 'runtime.constants' to '<...>/runtime/constants.py';
            # ambiguous suffixes (a bare `from . import constants`)
            # prefer the table closest to the importing module's dir
            suffix = target.lstrip(".").replace(".", "/") + ".py"
            src_dir = src.path.rsplit("/", 1)[0] if "/" in src.path else ""

            def _proximity(path):
                common = 0
                for a, b in zip(path.split("/"), src_dir.split("/")):
                    if a != b:
                        break
                    common += 1
                return common

            candidates = [p for p in tables if p.endswith(suffix)]
            if not candidates and len(tables) == 1:
                candidates = list(tables)
            if candidates:
                out[alias] = tables[max(candidates, key=_proximity)]
    return out


def _resolve_key(elt, const_aliases):
    """A known-set element to its key string, or None."""
    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
        return elt.value
    if isinstance(elt, ast.Attribute) and isinstance(elt.value, ast.Name):
        table = const_aliases.get(elt.value.id)
        if table is not None:
            return table.get(elt.attr)
    return None


def _parser_functions(src):
    """Function nodes that ARE the parse: contain a known-set assignment
    or are named like a parser."""
    out = set()
    for node in src.nodes():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name.startswith(("parse_", "_parse_")):
            out.add(node)
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and any(
                    isinstance(t, ast.Name)
                    and _KNOWN_SET_NAME.search(t.id)
                    for t in sub.targets):
                out.add(node)
                break
    return out


def _known_set_assignments(src):
    for node in src.nodes():
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _KNOWN_SET_NAME.search(node.targets[0].id) and \
                isinstance(node.value, ast.Set):
            yield node


@register
class ParseOnlyKeyRule(Rule):
    name = "parse-only-key"
    scope = "project"
    summary = ("config key accepted by a strict block parser with no "
               "read site anywhere else in the package — the knob "
               "parses, validates, and silently does nothing")
    incident = ("PR 9: the documented elasticity.supervisor block was "
                "parse-only for a whole PR; PR 5: a2a_overlap_chunks "
                "sat silently inert on the GSPMD path")

    def check_project(self, ctx):
        sources = ctx.sources
        tables = _constants_tables(sources)

        # -- harvest ------------------------------------------------------
        harvested = {}   # key -> list of (src, elt_node)
        for src in sources:
            const_aliases = _constants_aliases(src, tables)
            for assign in _known_set_assignments(src):
                for elt in assign.value.elts:
                    key = _resolve_key(elt, const_aliases)
                    if key is not None:
                        harvested.setdefault(key, []).append((src, elt))
        if not harvested:
            return

        # -- consumption scan --------------------------------------------
        consumed = set(_STRUCTURAL_KEYS)
        attr_reads = set()   # for the derived-attribute substring pass
        for src in sources:
            if "constants" in src.path.rsplit("/", 1)[-1]:
                continue
            const_aliases = _constants_aliases(src, tables)
            # flat membership set: every node under a parser function
            # (walking parent chains per node dominated the pass)
            parser_fns = _parser_functions(src)
            parser_nodes = set()
            for fn in parser_fns:
                parser_nodes.update(ast.walk(fn))

            def in_parser(node):
                return node in parser_nodes

            for node in src.nodes():
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.ctx, ast.Load):
                    if not in_parser(node):
                        consumed.add(node.attr)
                        attr_reads.add(node.attr)
                elif isinstance(node, ast.Subscript) and \
                        isinstance(node.ctx, ast.Load):
                    key = _resolve_key(node.slice, const_aliases)
                    if key is not None and not in_parser(node):
                        consumed.add(key)
                elif isinstance(node, ast.Call):
                    if not in_parser(node):
                        for kw in node.keywords:
                            if kw.arg:   # Thing(mfu=...) consumes 'mfu'
                                consumed.add(kw.arg)
                    # read the method name off the Attribute directly:
                    # `(d.get(a) or {}).get(b)` has no dotted root
                    tail = (node.func.attr
                            if isinstance(node.func, ast.Attribute)
                            else last_component(call_name(node)))
                    if tail in ("get", "pop", "setdefault") and node.args:
                        key = _resolve_key(node.args[0], const_aliases)
                        if key is not None and not in_parser(node):
                            consumed.add(key)
                    if isinstance(node.func, ast.Name) and \
                            node.func.id in ("getattr", "hasattr") and \
                            len(node.args) >= 2 and not in_parser(node):
                        key = _resolve_key(node.args[1], const_aliases)
                        if key is not None:
                            consumed.add(key)
                            attr_reads.add(key)
                elif isinstance(node, ast.Compare) and \
                        any(isinstance(op, (ast.In, ast.NotIn))
                            for op in node.ops):
                    key = _resolve_key(node.left, const_aliases)
                    if key is not None and not in_parser(node):
                        consumed.add(key)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    # def __init__(self, mfu=True): how **parsed_block
                    # expansion consumption manifests
                    if node not in parser_fns:
                        a = node.args
                        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                            consumed.add(arg.arg)

        def _derived_attr(key):
            return any(key in attr and key != attr for attr in attr_reads)

        # -- report -------------------------------------------------------
        for key in sorted(set(harvested) - consumed):
            if _derived_attr(key):
                continue
            for src, elt in harvested[key]:
                if src.suppressed(self.name, elt.lineno):
                    continue
                if src.annotated(CONSUMED_ANNOTATION, elt.lineno):
                    continue
                yield src.finding(
                    self.name, elt,
                    f"config key '{key}' is accepted by this strict "
                    f"parser but never read (no attribute/subscript/"
                    f".get site outside parse code): the knob silently "
                    f"does nothing. Wire it to a consumer, or mark the "
                    f"element line '# dslint: {CONSUMED_ANNOTATION}' if "
                    f"it is read outside the engine.")
