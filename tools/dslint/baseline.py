"""Committed baseline for grandfathered findings.

The baseline is a JSON file mapping findings (by rule + path +
content fingerprint, never line number) that are knowingly tolerated.
Policy (docs/static-analysis.md): the baseline should stay empty or
near-empty — a true positive gets *fixed*, a justified exception gets a
per-line suppression with a reason; the baseline exists so a new rule
can land gated before every historical finding is burned down, and so
CI can fail on *new* findings immediately.

Matching is count-aware: two identical offending lines in one file
share a fingerprint, and a baseline entry with ``"count": 2`` covers
exactly two live occurrences — a third is a new finding.
"""

import collections
import json
import os

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                                     "baseline.json")


def load_baseline(path):
    """{(rule, path, fingerprint): count}; an absent file is empty."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for entry in data.get("findings", []):
        key = (entry["rule"], entry["path"], entry["fingerprint"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def split_by_baseline(findings, baseline):
    """(new, grandfathered): consume baseline budget per fingerprint."""
    budget = dict(baseline)
    new, old = [], []
    for f in findings:
        key = (f.rule, f.path, f.fingerprint)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def write_baseline(findings, path, ruleset_version):
    """Regenerate the baseline from the current finding set (the
    intentional `--baseline-update` path). Atomic: a crash mid-write
    must not leave a torn baseline that silently ignores findings."""
    counter = collections.Counter(
        (f.rule, f.path, f.fingerprint) for f in findings)
    snippets = {}
    for f in findings:
        snippets.setdefault((f.rule, f.path, f.fingerprint), f.snippet)
    entries = [
        {"rule": rule, "path": p, "fingerprint": fp, "count": count,
         "snippet": snippets[(rule, p, fp)]}
        for (rule, p, fp), count in sorted(counter.items())
    ]
    payload = {
        "comment": ("Grandfathered dslint findings. Keep this empty: fix "
                    "true positives, suppress justified exceptions "
                    "per-line with a reason. See docs/static-analysis.md."),
        "ruleset": ruleset_version,
        "findings": entries,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return entries
