"""Lint driver: load sources, run every registered rule, apply the
baseline, return a structured result. The pytest tier-1 gate and the
``ds_lint`` CLI are both thin wrappers over :func:`run_lint`."""

import dataclasses
import os

from .baseline import (DEFAULT_BASELINE_PATH, load_baseline,
                       split_by_baseline)
from .core import LintContext, iter_source_files
from .rules import REGISTRY

# What the tier-1 gate lints. `bench.py` and `tests/perf/` ride along
# for the wall-clock audit (bench step timing on a wall clock is the
# same NTP-jump hazard PR 6 fixed in utils/timer.py) — and get the full
# rule set since they exercise the same engine surfaces.
DEFAULT_PATHS = ("deeperspeed_tpu", "bench.py", "tests/perf")


@dataclasses.dataclass
class LintResult:
    findings: list          # new (non-baselined) findings
    baselined: list         # findings covered by the committed baseline
    errors: list            # (path, message) unparseable files
    files_checked: int
    rules_run: list

    @property
    def ok(self):
        return not self.findings and not self.errors

    def to_dict(self, ruleset_version):
        return {
            "ruleset": ruleset_version,
            "ok": self.ok,
            "files_checked": self.files_checked,
            "rules": self.rules_run,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "errors": [{"path": p, "message": m} for p, m in self.errors],
        }


def run_lint(paths=None, root=None, select=None, baseline_path=None,
             use_baseline=True):
    """Run the rule set over ``paths`` (default: the tier-1 path set)
    relative to ``root`` (default: the repo root containing tools/).

    ``select``: optional iterable of rule names to run (others skipped).
    ``baseline_path``: None uses the committed tools/dslint/baseline.json;
    ``use_baseline=False`` reports every finding as new.
    """
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))

    ctx = LintContext(root=root)
    if paths:
        # an EXPLICIT path that doesn't exist must fail the run, not
        # silently lint 0 files with exit 0 (a typo'd pre-commit hook
        # would stop gating without anyone noticing)
        paths = list(paths)
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            if not os.path.exists(ap):
                ctx.errors.append((p, "path does not exist"))
    else:
        # default set: absent members are tolerated (a checkout without
        # bench.py still lints the package)
        paths = [p for p in DEFAULT_PATHS
                 if os.path.exists(os.path.join(root, p))]
    ctx.sources = list(iter_source_files(paths, root, errors=ctx.errors))

    rules = [r for name, r in sorted(REGISTRY.items())
             if select is None or name in set(select)]

    findings = []
    for rule in rules:
        if rule.scope == "project":
            findings.extend(rule.check_project(ctx))
        else:
            for src in ctx.sources:
                findings.extend(rule.check_file(src, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if use_baseline:
        bpath = baseline_path or DEFAULT_BASELINE_PATH
        new, old = split_by_baseline(findings, load_baseline(bpath))
    else:
        new, old = findings, []
    return LintResult(findings=new, baselined=old, errors=list(ctx.errors),
                      files_checked=len(ctx.sources),
                      rules_run=[r.name for r in rules])
