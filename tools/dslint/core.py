"""dslint core: findings, per-line suppressions, source-file loading.

A finding is identified across edits by a *fingerprint* — a hash of
(rule, path, stripped source line) — not by its line number, so an
unrelated edit above a grandfathered finding does not invalidate the
committed baseline. Two identical offending lines in one file share a
fingerprint; the baseline matcher is count-aware (see baseline.py).
"""

import ast
import dataclasses
import hashlib
import io
import os
import re
import tokenize

# `# dslint: disable=rule-a,rule-b` — suppresses those rules on the
# same line, or on the following line when the comment stands alone.
# `# dslint: disable-file=rule-a` anywhere suppresses for the file.
_SUPPRESS_RE = re.compile(
    r"#\s*dslint:\s*disable(?P<file>-file)?\s*=\s*(?P<rules>[\w,\- ]+)")
# `# dslint: consumed-by-launcher` — annotation escape for config keys
# that are read outside the engine package (launcher, external tooling);
# recognized by the parse-only-key pass, not a generic suppression.
_ANNOTATION_RE = re.compile(r"#\s*dslint:\s*(?P<note>[a-z][\w\-]*)\b")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-root-relative, forward slashes
    line: int          # 1-based
    col: int
    message: str
    snippet: str = ""  # the offending source line, stripped

    @property
    def fingerprint(self):
        payload = f"{self.rule}\x00{self.path}\x00{self.snippet.strip()}"
        return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]

    def to_dict(self):
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }

    def render(self):
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}")


class SourceFile:
    """A parsed module plus its comment directives.

    ``suppressions``/``annotations`` map 1-based line numbers to the
    rule names / note tags attached to that line. A directive on a
    comment-only line applies to the next line as well (for findings on
    lines too long to carry a trailing comment).
    """

    def __init__(self, abspath, relpath, text):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)   # SyntaxError propagates to caller
        self.suppressions = {}        # line -> set of rule names
        self.file_suppressions = set()
        self.annotations = {}         # line -> set of note tags
        self._scan_directives()
        self._parents = None
        self._nodes = None
        self._aliases = None

    def _scan_directives(self):
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            standalone = self.lines[line - 1].lstrip().startswith("#")
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group("rules").split(",")
                         if r.strip()}
                if m.group("file"):
                    self.file_suppressions |= rules
                else:
                    self.suppressions.setdefault(line, set()).update(rules)
                    if standalone:
                        self.suppressions.setdefault(
                            line + 1, set()).update(rules)
                continue
            m = _ANNOTATION_RE.search(tok.string)
            if m and m.group("note") != "disable":
                self.annotations.setdefault(line, set()).add(m.group("note"))
                if standalone:
                    self.annotations.setdefault(
                        line + 1, set()).add(m.group("note"))

    def suppressed(self, rule, line):
        if rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        rules = self.suppressions.get(line, ())
        return rule in rules or "all" in rules

    def annotated(self, note, line):
        return note in self.annotations.get(line, ())

    def line_text(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def nodes(self):
        """Flat node list, walked once per file (every rule iterates
        the whole tree; re-walking per rule dominated lint time)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def aliases(self):
        """Import alias map (resolve.import_aliases), cached."""
        if self._aliases is None:
            from .resolve import import_aliases
            self._aliases = import_aliases(self)
        return self._aliases

    def parents(self):
        """node -> parent map, built lazily once per file."""
        if self._parents is None:
            self._parents = {}
            for parent in self.nodes():
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def finding(self, rule, node, message):
        return Finding(rule=rule, path=self.path, line=node.lineno,
                       col=node.col_offset + 1, message=message,
                       snippet=self.line_text(node.lineno))


@dataclasses.dataclass
class LintContext:
    """Shared state handed to every rule invocation."""
    root: str                      # repo root all paths are relative to
    sources: list = None           # every SourceFile in this run
    errors: list = None            # (path, message) for unparseable files

    def __post_init__(self):
        if self.sources is None:
            self.sources = []
        if self.errors is None:
            self.errors = []


_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules",
              ".ipynb_checkpoints"}


def iter_python_files(paths, root):
    """Yield absolute paths of .py files under ``paths`` (files or
    directories, relative to ``root`` unless absolute), sorted for
    deterministic reports."""
    out = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                out.append(ap)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    seen = set()
    for ap in out:
        real = os.path.normpath(ap)
        if real not in seen:
            seen.add(real)
            yield real


def iter_source_files(paths, root, errors=None):
    """Load every lintable file into a SourceFile; unparseable files are
    recorded in ``errors`` (they must fail the gate loudly, not vanish
    from coverage)."""
    for abspath in iter_python_files(paths, root):
        relpath = os.path.relpath(abspath, root)
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                text = f.read()
            yield SourceFile(abspath, relpath, text)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            if errors is not None:
                errors.append((relpath.replace(os.sep, "/"), str(e)))
