"""``python -m tools.dslint`` — see cli.py."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
