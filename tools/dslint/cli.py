"""``ds_lint`` — the dslint command line (mirrors ``ds_report``).

    ds_lint [paths...]          lint (default: tier-1 path set), text report
    ds_lint --json              machine-readable findings on stdout
    ds_lint --baseline-update   regenerate the committed baseline from the
                                current finding set (intentional act)
    ds_lint --list-rules        print the rule catalog
    ds_lint --select a,b        run only the named rules

Exit code 0 when no non-baselined findings (and no unparseable files),
1 otherwise — usable directly as a pre-commit hook or CI step.
"""

import argparse
import json
import sys

from . import RULESET_VERSION
from .baseline import DEFAULT_BASELINE_PATH, write_baseline
from .engine import DEFAULT_PATHS, run_lint
from .rules import REGISTRY


def _print_text(result, show_baselined):
    for path, message in result.errors:
        print(f"{path}: [parse-error] {message}")
    for f in result.findings:
        print(f.render())
        if f.snippet:
            print(f"    {f.snippet}")
    if show_baselined:
        for f in result.baselined:
            print(f"{f.render()}  (baselined)")
    status = "clean" if result.ok else "FAILED"
    print(f"dslint {RULESET_VERSION}: {len(result.findings)} finding(s), "
          f"{len(result.baselined)} baselined, {len(result.errors)} parse "
          f"error(s) over {result.files_checked} file(s) — {status}")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_lint",
        description="DeeperSpeed-TPU repo-native static analysis")
    parser.add_argument("paths", nargs="*",
                        help=f"files/dirs to lint (default: "
                             f"{' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--root", default=None,
                        help="repo root paths are relative to "
                             "(default: the checkout containing tools/)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable report on stdout")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule names to run")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: "
                             f"{DEFAULT_BASELINE_PATH})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding")
    parser.add_argument("--baseline-update", action="store_true",
                        help="rewrite the baseline to cover the current "
                             "finding set (intentional re-baseline)")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also print grandfathered findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in sorted(REGISTRY):
            rule = REGISTRY[name]
            print(f"{name} [{rule.scope}]")
            print(f"    {rule.summary}")
            if rule.incident:
                print(f"    incident: {rule.incident}")
        return 0

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        unknown = sorted(set(select) - set(REGISTRY))
        if unknown:
            print(f"ds_lint: unknown rule(s) {unknown}; valid: "
                  f"{sorted(REGISTRY)}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or DEFAULT_BASELINE_PATH
    result = run_lint(paths=args.paths or None, root=args.root,
                      select=select, baseline_path=baseline_path,
                      use_baseline=not args.no_baseline
                      and not args.baseline_update)

    if args.baseline_update:
        entries = write_baseline(result.findings, baseline_path,
                                 RULESET_VERSION)
        print(f"ds_lint: baseline rewritten with {len(entries)} entry "
              f"group(s) covering {len(result.findings)} finding(s) -> "
              f"{baseline_path}")
        return 0

    if args.as_json:
        print(json.dumps(result.to_dict(RULESET_VERSION), indent=2))
    else:
        _print_text(result, args.show_baselined)
    return 0 if result.ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
