"""Scope and name resolution shared by the rules.

AST-only, intentionally conservative: dslint never imports the code it
lints. Alias maps come from the module's own import statements, traced
scopes from decorator/call-site syntax. When resolution is uncertain the
helpers answer "not traced"/"unknown" — a lint rule must miss an exotic
construction rather than fabricate a finding.
"""

import ast

# Wrapping one of these around a function makes its body traced code:
# host-side calls inside run at trace time (once, at compile) — or not
# at all — never per step.
JIT_MARKERS = {"jit", "pjit", "pallas_call", "shard_map", "named_call"}

# Functions handed to these run host-side even when lexically nested in
# a traced function (jax.debug.callback / io_callback / pure_callback /
# jax.debug.print's callee, host_callback.call).
_CALLBACK_TOKEN = "callback"


def dotted_name(node):
    """'jax.jit' for Attribute chains / Names; None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node):
    """Dotted name of a Call's callee (unwrapping nothing)."""
    if isinstance(node, ast.Call):
        return dotted_name(node.func)
    return None


def last_component(dotted):
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _iter_nodes(src_or_tree):
    """Accept a SourceFile (cached flat node list) or a bare AST node."""
    if hasattr(src_or_tree, "nodes"):
        return src_or_tree.nodes()
    return ast.walk(src_or_tree)


def import_aliases(tree):
    """Map local alias -> imported dotted module/name.

    ``import time as _time`` -> {'_time': 'time'};
    ``from jax.experimental import pallas as pl`` ->
    {'pl': 'jax.experimental.pallas'};
    ``from time import monotonic`` -> {'monotonic': 'time.monotonic'}.
    Relative imports keep their leading dots ('.constants').
    """
    aliases = {}
    for node in _iter_nodes(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    aliases[head] = head
        elif isinstance(node, ast.ImportFrom):
            mod = "." * node.level + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                # dot-join unless mod is empty or bare dots (`from .
                # import x` must give '.x', not '..x')
                sep = "." if mod and not mod.endswith(".") else ""
                aliases[a.asname or a.name] = f"{mod}{sep}{a.name}"
    return aliases


def resolve_dotted(aliases, dotted):
    """Substitute the first component of ``dotted`` through the module's
    alias map: with ``import time as _time``, '_time.time' resolves to
    'time.time'."""
    if not dotted:
        return dotted
    head, _, rest = dotted.partition(".")
    real = aliases.get(head, head)
    return f"{real}.{rest}" if rest else real


def _is_jit_marker(dotted):
    return last_component(dotted) in JIT_MARKERS


def _decorator_markers(dec):
    """Dotted names asserted by one decorator expression, unwrapping
    ``partial(jax.jit, ...)`` to inspect its arguments too."""
    names = []
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func)
        if fn:
            names.append(fn)
        if last_component(fn) == "partial":
            for arg in dec.args:
                sub = dotted_name(arg)
                if sub:
                    names.append(sub)
    else:
        d = dotted_name(dec)
        if d:
            names.append(d)
    return names


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class TracedScopes:
    """Classify every function node in a module as traced / host.

    A function is a *traced root* when it carries a jit-marker decorator
    (directly or through ``partial``) or is passed by name/lambda to a
    jit-marker call (``jax.jit(f)``, ``shard_map(f, mesh=...)``,
    ``pl.pallas_call(kernel, ...)``). A function passed to a
    callback-flavored call is a *host root* — it runs on the host even
    inside a traced scope. Everything else inherits the nearest marked
    ancestor's classification.
    """

    def __init__(self, src):
        self.src = src
        self.parents = src.parents()
        self._traced_roots = set()
        self._host_roots = set()
        self._classify()

    def _defs_by_name(self):
        by_name = {}
        for node in self.src.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        return by_name

    def _classify(self):
        by_name = self._defs_by_name()
        for node in self.src.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if any(_is_jit_marker(n) for n in _decorator_markers(dec)):
                        self._traced_roots.add(node)
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node)
            is_jit = _is_jit_marker(callee)
            is_cb = (last_component(callee) or "").find(_CALLBACK_TOKEN) >= 0
            if not (is_jit or is_cb):
                continue
            cands = list(node.args) + [kw.value for kw in node.keywords]
            for arg in cands:
                targets = []
                if isinstance(arg, ast.Lambda):
                    targets = [arg]
                elif isinstance(arg, ast.Name):
                    targets = by_name.get(arg.id, [])
                for t in targets:
                    (self._traced_roots if is_jit
                     else self._host_roots).add(t)

    def enclosing_function(self, node):
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _FUNC_NODES):
            cur = self.parents.get(cur)
        return cur

    def is_traced(self, node):
        """True when ``node`` sits inside traced code: walk outward from
        its enclosing function; the first traced/host root met decides."""
        fn = node if isinstance(node, _FUNC_NODES) \
            else self.enclosing_function(node)
        while fn is not None:
            if fn in self._host_roots:
                return False
            if fn in self._traced_roots:
                return True
            fn = self.enclosing_function(fn)
        return False


def thread_target_functions(src):
    """Function defs passed as ``target=`` to ``threading.Thread(...)``
    (by local name), plus every def nested inside one — the scope where
    a swallowed exception dies silently instead of crashing the run."""
    parents = src.parents()
    by_name = {}
    for node in src.nodes():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    roots = set()
    for node in src.nodes():
        if not isinstance(node, ast.Call):
            continue
        if last_component(call_name(node)) != "Thread":
            continue
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and node.args:  # Thread(group, target, ...)
            target = node.args[1] if len(node.args) > 1 else None
        if isinstance(target, ast.Name):
            roots.update(by_name.get(target.id, []))
    members = set(roots)
    for node in src.nodes():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cur = parents.get(node)
        while cur is not None:
            if cur in roots:
                members.add(node)
                break
            cur = parents.get(cur)
    return members
