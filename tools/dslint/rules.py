"""The dslint rule catalog.

Every rule encodes a defect class this repo has actually shipped and
had to fix in review; the ``incident`` string names the PR that paid
for it. See ``docs/static-analysis.md`` for the full catalog and the
policy on suppressions vs. baseline entries.

File rules run per module; project rules (``scope = "project"``) see
every parsed module at once — the parse-only config-key pass lives in
``config_keys.py`` and registers here.
"""

import ast

from .resolve import (TracedScopes, call_name, import_aliases,
                      last_component, resolve_dotted,
                      thread_target_functions)


class Rule:
    name = ""
    summary = ""
    incident = ""
    scope = "file"           # or "project"

    def check_file(self, src, ctx):
        return ()

    def check_project(self, ctx):
        return ()


REGISTRY = {}


def register(cls):
    rule = cls()
    assert rule.name and rule.name not in REGISTRY
    REGISTRY[rule.name] = rule
    return cls


def _emit(src, rule, node, message):
    if not src.suppressed(rule, node.lineno):
        yield src.finding(rule, node, message)


# ---------------------------------------------------------------------------
# 1. trace-unsafe host calls inside jitted / shard_mapped / Pallas code
# ---------------------------------------------------------------------------

@register
class TraceHostCallRule(Rule):
    name = "trace-host-call"
    summary = ("host-side call (time/random/np.random/print/open) inside "
               "a function traced by jax.jit/shard_map/pallas_call")
    incident = ("traced host calls run once at compile time (or never), "
                "not per step — timing/randomness silently freezes, "
                "I/O silently disappears")

    _BANNED_PREFIXES = ("time.", "random.", "numpy.random.")
    _BANNED_BUILTINS = {"print", "open", "input"}

    def check_file(self, src, ctx):
        scopes = TracedScopes(src)
        aliases = src.aliases()
        for node in src.nodes():
            if not isinstance(node, ast.Call):
                continue
            dotted = resolve_dotted(aliases, call_name(node))
            bad = None
            if dotted in self._BANNED_BUILTINS:
                # builtin by bare name only — a method `.print()` or a
                # local override is not the builtin
                if isinstance(node.func, ast.Name):
                    bad = dotted
            elif dotted and any(dotted.startswith(p)
                                for p in self._BANNED_PREFIXES):
                bad = dotted
            if bad and scopes.is_traced(node):
                yield from _emit(
                    src, self.name, node,
                    f"'{bad}(...)' inside traced code: this executes at "
                    f"trace time, not per step. Use jax.debug.callback / "
                    f"jax PRNG keys, or hoist it out of the jitted scope.")


# ---------------------------------------------------------------------------
# 2. wall-clock ban: time.time() outside annotated true-timestamp sites
# ---------------------------------------------------------------------------

@register
class WallClockRule(Rule):
    name = "wall-clock"
    summary = ("time.time() used where an interval is measured — NTP "
               "steps corrupt wall-clock deltas; use time.monotonic()")
    incident = ("PR 6: utils/timer.py measured step time on time.time(); "
                "an NTP jump corrupted elapsed/samples-per-sec")

    def check_file(self, src, ctx):
        aliases = src.aliases()
        for node in src.nodes():
            if not isinstance(node, ast.Call):
                continue
            dotted = resolve_dotted(aliases, call_name(node))
            if dotted == "time.time":
                yield from _emit(
                    src, self.name, node,
                    "time.time() is wall-clock and jumps with NTP: use "
                    "time.monotonic() for intervals. A true epoch "
                    "timestamp site must carry "
                    "'# dslint: disable=wall-clock  (why)'.")


# ---------------------------------------------------------------------------
# 3. strong-ref lifecycle hooks (atexit/signal holding bound methods)
# ---------------------------------------------------------------------------

@register
class StrongRefHookRule(Rule):
    name = "strong-ref-hook"
    summary = ("atexit.register/signal.signal given a bound method — the "
               "registry pins the owner (engine/monitor/manager) for the "
               "process lifetime; use runtime.utils.register_weak_atexit "
               "or a weakref-bound handler")
    incident = ("PRs 3/4/6/9: atexit + signal registries kept whole "
                "engines alive across bench ladders and tests")

    @staticmethod
    def _module_paths(ctx):
        """Dotted-path set of every linted module, cached on the run
        context (used to tell `from pkg import module` apart from
        `from pkg import OBJECT` — only the former's attributes are
        module functions, not bound methods)."""
        paths = getattr(ctx, "_dslint_module_paths", None)
        if paths is None:
            paths = set()
            for s in ctx.sources:
                p = s.path[:-3] if s.path.endswith(".py") else s.path
                if p.endswith("/__init__"):
                    p = p[:-len("/__init__")]
                paths.add(p)
            ctx._dslint_module_paths = paths
        return paths

    def _is_module_base(self, base, src, ctx, plain_imports, from_targets):
        """True only when ``base`` provably names a MODULE: a plain
        ``import x [as y]`` alias (always a module), or a from-import
        whose target resolves to a module file in the linted set. A
        from-imported NAME that is an object (engine/monitor instance)
        stays flagged — that is exactly the incident class."""
        if not isinstance(base, ast.Name):
            return False
        if base.id in plain_imports:
            return True
        target = from_targets.get(base.id)
        if target is None:
            return False
        paths = self._module_paths(ctx)
        if target.startswith("."):
            level = len(target) - len(target.lstrip("."))
            rest = target.lstrip(".")
            base_dir = src.path.rsplit("/", 1)[0] if "/" in src.path else ""
            for _ in range(level - 1):
                base_dir = base_dir.rsplit("/", 1)[0] \
                    if "/" in base_dir else ""
            cand = (f"{base_dir}/" if base_dir else "") + \
                rest.replace(".", "/")
            return cand in paths
        cand = target.replace(".", "/")
        return any(p == cand or p.endswith("/" + cand) for p in paths)

    def check_file(self, src, ctx):
        aliases = src.aliases()
        plain_imports = set()
        from_targets = {}
        for node in src.nodes():
            if isinstance(node, ast.Import):
                for a in node.names:
                    plain_imports.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                mod = "." * node.level + (node.module or "")
                for a in node.names:
                    if a.name != "*":
                        # dot-join unless mod is empty or bare dots
                        # (`from . import x` must give '.x', not '..x')
                        sep = "." if mod and not mod.endswith(".") else ""
                        from_targets[a.asname or a.name] = \
                            f"{mod}{sep}{a.name}"
        for node in src.nodes():
            if not isinstance(node, ast.Call):
                continue
            dotted = resolve_dotted(aliases, call_name(node))
            if dotted == "atexit.register":
                handlers = node.args[:1]
                what = "atexit.register"
            elif dotted == "signal.signal":
                handlers = node.args[1:2]
                what = "signal.signal"
            else:
                continue
            for h in handlers:
                if not isinstance(h, ast.Attribute):
                    continue
                if self._is_module_base(h.value, src, ctx,
                                        plain_imports, from_targets):
                    continue
                yield from _emit(
                    src, self.name, h,
                    f"{what} holds a strong reference to bound method "
                    f"'{ast.unparse(h)}': the registry pins its owner "
                    f"for the process lifetime. Route through "
                    f"register_weak_atexit / bind via weakref.")


# ---------------------------------------------------------------------------
# 4. non-atomic writes into checkpoint/save directories
# ---------------------------------------------------------------------------

_CKPT_TOKENS = ("ckpt", "checkpoint", "save_dir", "snapshot", "latest")
_SAFE_TOKENS = ("staging", "tmp", "temp", ".part")


@register
class NonAtomicCommitRule(Rule):
    name = "non-atomic-commit"
    summary = ("direct write into a checkpoint/save path without the "
               "staging-sibling + os.replace commit discipline")
    incident = ("PR 3: `latest` was rewritten in place pre-barrier — a "
                "crash mid-write left a torn pointer that read as a "
                "corrupt checkpoint")

    def _path_expr(self, node, dotted):
        tail = last_component(dotted)
        if tail == "open" and isinstance(node.func, ast.Name):
            if len(node.args) >= 2:
                mode = node.args[1]
                if isinstance(mode, ast.Constant) and \
                        isinstance(mode.value, str) and "w" in mode.value:
                    return node.args[0]
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                        and "w" in str(kw.value.value):
                    return node.args[0] if node.args else None
            return None
        if dotted in ("numpy.save", "numpy.savez", "numpy.savez_compressed"):
            return node.args[0] if node.args else None
        return None

    def check_file(self, src, ctx):
        aliases = src.aliases()
        parents = src.parents()

        def enclosing_body(node):
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = parents.get(cur)
            return cur if cur is not None else src.tree

        def has_replace(scope):
            for n in ast.walk(scope):
                if isinstance(n, ast.Call) and \
                        resolve_dotted(aliases, call_name(n)) in (
                            "os.replace", "os.rename"):
                    return True
            return False

        for node in src.nodes():
            if not isinstance(node, ast.Call):
                continue
            dotted = resolve_dotted(aliases, call_name(node))
            path_expr = self._path_expr(node, dotted)
            if path_expr is None:
                continue
            try:
                path_src = ast.unparse(path_expr).lower()
            except Exception:  # pragma: no cover - exotic expr
                continue
            if not any(t in path_src for t in _CKPT_TOKENS):
                continue
            if any(t in path_src for t in _SAFE_TOKENS):
                continue
            if has_replace(enclosing_body(node)):
                continue
            yield from _emit(
                src, self.name, node,
                f"write targets checkpoint-flavored path "
                f"({ast.unparse(path_expr)}) with no staging sibling + "
                f"os.replace in scope: a crash mid-write leaves a torn "
                f"file that later reads as a corrupt checkpoint. Write "
                f"to '<path>.tmp'/staging and os.replace() it in.")


# ---------------------------------------------------------------------------
# 5. coordination-service barriers without a deadline
# ---------------------------------------------------------------------------

@register
class BarrierNoDeadlineRule(Rule):
    name = "barrier-no-deadline"
    summary = ("wait_at_barrier / blocking KV wait without a timeout — a "
               "dead peer hangs the job forever instead of failing typed")
    incident = ("PR 9: commit barriers gained a deadline floor so a dead "
                "host fails the commit fast instead of wedging every "
                "peer in wait_at_barrier")

    _WAITERS = {"wait_at_barrier", "blocking_key_value_get"}
    _TIMEOUT_KWS = {"timeout", "timeout_in_ms", "timeout_ms", "deadline",
                    "timeout_s"}

    def check_file(self, src, ctx):
        for node in src.nodes():
            if not isinstance(node, ast.Call):
                continue
            tail = last_component(call_name(node))
            if tail not in self._WAITERS:
                continue
            if len(node.args) >= 2:
                continue
            if any(kw.arg in self._TIMEOUT_KWS for kw in node.keywords):
                continue
            yield from _emit(
                src, self.name, node,
                f"'{tail}' call without a deadline: a missing peer hangs "
                f"this host forever. Thread a timeout (the commit-barrier "
                f"floor is DEFAULT_BARRIER_TIMEOUT_S).")


# ---------------------------------------------------------------------------
# 6. swallowed exceptions inside thread targets / daemon loops
# ---------------------------------------------------------------------------

@register
class SwallowedThreadExcRule(Rule):
    name = "swallowed-thread-exc"
    summary = ("`except Exception: pass` inside a threading.Thread target "
               "— the daemon dies or corrupts state with no trace")
    incident = ("PR 9: a gRPC failure silently killed the peer-health "
                "poll thread — the exact dead-coordinator case the "
                "subsystem existed to catch")

    _BROAD = {"Exception", "BaseException"}

    def check_file(self, src, ctx):
        targets = thread_target_functions(src)
        if not targets:
            return
        for fn in targets:
            for node in ast.walk(fn):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is not None:
                    tname = last_component(
                        ast.unparse(node.type)) if node.type else None
                    if tname not in self._BROAD:
                        continue
                if all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in node.body):
                    yield from _emit(
                        src, self.name, node,
                        "broad except with an empty body inside a thread "
                        "target: the failure vanishes and the loop keeps "
                        "running (or dies) silently. Log it, count it, or "
                        "escalate it — never drop it.")


# ---------------------------------------------------------------------------
# 7. timed measurement over Pallas calls without an interpret-mode guard
# ---------------------------------------------------------------------------

@register
class TimedPallasNoInterpretRule(Rule):
    name = "timed-pallas-no-interpret"
    summary = ("monotonic-delta / timeit measurement over a Pallas call "
               "with no interpret-mode bail-out — on CPU this times the "
               "Pallas interpreter, minutes per candidate")
    incident = ("PR 7: the flash fwd block tuner had no interpret guard; "
                "a 16k-seq CPU dispatch measured interpreter candidates "
                "for 58 minutes")

    _CLOCKS = {"time.monotonic", "time.perf_counter", "timeit.timeit",
               "timeit.repeat"}

    def _timing_calls(self, fn, aliases):
        out = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                dotted = resolve_dotted(aliases, call_name(node))
                if dotted in self._CLOCKS:
                    out.append(node)
        return out

    def _mentions_interpret(self, fn):
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and "interpret" in node.id.lower():
                return True
            if isinstance(node, ast.Attribute) and \
                    "interpret" in node.attr.lower():
                return True
        return False

    def _touches_pallas(self, fn, aliases):
        for node in ast.walk(fn):
            dotted = None
            if isinstance(node, ast.Call):
                dotted = resolve_dotted(aliases, call_name(node))
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = [a.name for a in node.names]
                if isinstance(node, ast.ImportFrom) and node.module:
                    mods.append(node.module)
                if any("pallas" in m for m in mods):
                    return True
            if dotted and "pallas" in dotted:
                return True
            if isinstance(node, ast.Name) and \
                    node.id in aliases and "pallas" in aliases[node.id]:
                return True
        return False

    def check_file(self, src, ctx):
        aliases = src.aliases()
        fns = [n for n in src.nodes()
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        by_name = {}
        for fn in fns:
            by_name.setdefault(fn.name, []).append(fn)

        def callers_guarded(fn):
            """One level up: every in-module caller mentions interpret
            (the autotune pattern — the public tuner guards, a private
            _measure helper does the timing)."""
            callers = []
            for other in fns:
                if other is fn:
                    continue
                for node in ast.walk(other):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Name) and \
                            node.func.id == fn.name:
                        callers.append(other)
                        break
            return bool(callers) and all(self._mentions_interpret(c)
                                         for c in callers)

        for fn in fns:
            timing = self._timing_calls(fn, aliases)
            if len(timing) < 2 and not any(
                    resolve_dotted(aliases, call_name(t)).startswith(
                        "timeit.") for t in timing):
                continue
            if not self._touches_pallas(fn, aliases):
                continue
            if self._mentions_interpret(fn) or callers_guarded(fn):
                continue
            yield from _emit(
                src, self.name, timing[0],
                f"'{fn.name}' times a Pallas-flavored call with no "
                f"interpret-mode bail-out: on CPU this measures the "
                f"Pallas interpreter (minutes per candidate). Check "
                f"`_interpret()` / interpret mode and return the "
                f"deterministic default first.")


# ---------------------------------------------------------------------------
# 8. collectives issued outside the schedule pass in slice-aware code
# ---------------------------------------------------------------------------

@register
class MultisliceCollectiveRule(Rule):
    name = "multislice-collective-outside-schedule"
    summary = ("jax.lax collective issued outside the schedule pass in "
               "slice-aware code — DCN wire ops must route through the "
               "schedule/transport layer")
    incident = ("PR 19 (docs/multislice.md): a collective issued "
                "directly from slice-management code bypasses the DCN "
                "wire policy (fp32 refusal, packed signs, exposed-"
                "crossing accounting) — it would silently ship "
                "uncompressed fp32 over the slow fabric")

    # the schedule pass + transport layer, where collectives BELONG
    _SCHEDULE_PATHS = (
        "deeperspeed_tpu/parallel/schedule.py",
        "deeperspeed_tpu/parallel/pipeline_spmd.py",
        "deeperspeed_tpu/runtime/comm/",
        "deeperspeed_tpu/runtime/pipe/",
    )
    # modules whose code is slice-aware in its entirety
    _SLICE_MODULES = ("parallel/multislice.py", "elasticity/slices.py")
    _COLLECTIVES = {
        "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
        "jax.lax.psum_scatter", "jax.lax.all_gather",
        "jax.lax.all_to_all", "jax.lax.ppermute",
    }

    def _is_slice_aware(self, fn, aliases):
        """Does this function reference the multislice layer — an
        imported multislice/slices name, or an in-function import of
        one of those modules?"""
        for node in ast.walk(fn):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = [a.name for a in node.names]
                if isinstance(node, ast.ImportFrom) and node.module:
                    mods.append(node.module)
                if any("multislice" in m or m.endswith("slices")
                       for m in mods):
                    return True
            elif isinstance(node, ast.Name):
                dotted = aliases.get(node.id, "")
                if "multislice" in dotted or \
                        dotted.endswith(("elasticity.slices", ".slices")):
                    return True
        return False

    def _collective_calls(self, root, aliases):
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                dotted = resolve_dotted(aliases, call_name(node))
                if dotted in self._COLLECTIVES:
                    yield node, dotted

    def check_file(self, src, ctx):
        if any(p in src.path for p in self._SCHEDULE_PATHS):
            return
        aliases = src.aliases()
        whole_module = any(src.path.endswith(m)
                           for m in self._SLICE_MODULES)
        seen = set()
        roots = [src.tree] if whole_module else [
            n for n in src.nodes()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and self._is_slice_aware(n, aliases)]
        for root in roots:
            for node, dotted in self._collective_calls(root, aliases):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                yield from _emit(
                    src, self.name, node,
                    f"'{dotted}(...)' issued from slice-aware code "
                    f"outside the schedule pass: route the wire op "
                    f"through parallel/schedule.py / runtime/comm / "
                    f"runtime/pipe so the DCN policy (fp32 refusal, "
                    f"packed signs, crossing accounting) applies.")


# Project-scope rule 9 registers itself on import.
from . import config_keys  # noqa: E402,F401  (registration side effect)
