"""dslint — repo-native static analysis for DeeperSpeed-TPU.

Thirteen PRs of review history show the same host-side defect classes
recurring: parse-only config knobs that silently do nothing, strong-ref
atexit/signal handlers that pin engines for the process lifetime,
non-atomic writes into checkpoint directories, wall-clock timers that
jump with NTP, timed autotune loops that measure the Pallas interpreter
for minutes on CPU, and daemon threads that swallow their own death.
Each of these invariants is mechanically checkable, so dslint checks
them mechanically — in tier-1, before any TPU is touched.

Usage:

    python -m tools.dslint                 # lint the default path set
    python -m tools.dslint deeperspeed_tpu # lint one tree
    bin/ds_lint --json                     # machine-readable findings
    bin/ds_lint --baseline-update          # intentionally re-baseline

The rule catalog lives in ``docs/static-analysis.md``; suppression is
per-line (``# dslint: disable=<rule>``) and grandfathered findings live
in the committed ``tools/dslint/baseline.json``.
"""

# Bumped whenever a rule is added/removed or a rule's detection surface
# changes materially. `ds_report --json` embeds this in the environment
# fingerprint so a fleet trace records which invariant set the producing
# checkout was linted against.
RULESET_VERSION = "1.0"

from .core import Finding, LintContext, SourceFile, iter_source_files  # noqa: E402
from .engine import DEFAULT_PATHS, run_lint  # noqa: E402
from .rules import REGISTRY  # noqa: E402

__all__ = [
    "RULESET_VERSION",
    "Finding",
    "LintContext",
    "SourceFile",
    "iter_source_files",
    "run_lint",
    "DEFAULT_PATHS",
    "REGISTRY",
]
