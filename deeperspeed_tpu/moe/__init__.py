from .layer import MoELayer, moe_ffn_dense, moe_ffn_expert_parallel

__all__ = ["MoELayer", "moe_ffn_dense", "moe_ffn_expert_parallel"]
