from .layer import (DISPATCH_MODES, MoELayer, moe_ffn_dense,
                    moe_ffn_expert_parallel)

__all__ = ["DISPATCH_MODES", "MoELayer", "moe_ffn_dense",
           "moe_ffn_expert_parallel"]
