"""Mixture-of-Experts FFN with expert parallelism.

The reference (DeepSpeed v0.3.15) predates MoE; this layer exists so the
framework covers the modern 4th parallel axis alongside dp/pp/tp/sp. The
design is the GShard/Switch pattern, TPU-first:

- **top-1 gating with capacity**: each token routes to its argmax expert;
  an expert accepts at most `capacity` tokens (position-ordered).
  Overflow tokens combine to an exact-zero output — the surrounding
  transformer block's residual connection is what carries them through
  unchanged (standard Switch/GShard usage; this layer does NOT add the
  residual itself). Static shapes — the dispatch is a dense [T, E, C]
  one-hot combine/dispatch pair, exactly the formulation GShard lowers
  to XLA.
- **token groups** (`groups`): GShard's G dimension. Tokens split into
  `g` independent routing groups with per-group capacity C/g, shrinking
  the dispatch/combine tensors from O(T·E·C) to O(T·E·C/g) — the
  ungrouped form OOMs a 16 GB chip at T=8k/H=768, the grouped form is
  O(group_size) and stays pure einsum (MXU work, no scatter). `groups=1`
  is the exact ungrouped oracle; `groups=0` ("auto") picks the divisor
  of T whose group size is NEAREST `_AUTO_GROUP_TOKENS` (1024) and at
  least 128 — the size may exceed 1024 when T has no nearby divisor
  (e.g. T=2500 groups at 1250), trading a looser memory bound for
  routing-statistics quality over tiny groups.
- **expert parallelism**: experts shard over an ``expert`` mesh axis
  inside `shard_map`; token shards are exchanged with `all_to_all`
  (dispatch) and returned (combine), both riding ICI.
- Gate math in fp32; an auxiliary load-balancing loss (mean_prob ×
  mean_assignment per expert, scaled by E) is returned for the trainer.

`moe_ffn_dense` is the single-device reference semantics;
`moe_ffn_expert_parallel` runs inside `shard_map` and matches it
exactly (tested on the 8-device mesh).
"""

import jax
import jax.numpy as jnp

# auto-grouping target: the largest per-group token count. 1024 keeps the
# per-layer dispatch+combine pair ≈ E·C·T·4B ≈ tens of MB at GPT scales
# while each group is still large enough for balanced routing statistics.
_AUTO_GROUP_TOKENS = 1024


def _resolve_groups(groups, tokens):
    """0/'auto' → the divisor of `tokens` whose group size is nearest
    `_AUTO_GROUP_TOKENS` (never below 128: a token count with only tiny
    divisors near the target — e.g. 2·1031 — would otherwise shrink
    capacity to ~1 and silently drop routed tokens); otherwise validate
    the explicit count."""
    if groups in (0, None, "auto"):
        best_g, best_cost = 1, abs(tokens - _AUTO_GROUP_TOKENS)
        d = 1
        while d * d <= tokens:
            if tokens % d == 0:
                for g in (d, tokens // d):
                    size = tokens // g
                    if size < 128:
                        continue
                    cost = abs(size - _AUTO_GROUP_TOKENS)
                    if cost < best_cost or (cost == best_cost
                                            and g > best_g):
                        best_g, best_cost = g, cost
            d += 1
        return best_g
    groups = int(groups)
    if groups < 1 or tokens % groups:
        raise ValueError(f"groups={groups} must be ≥1 and divide the "
                         f"token count {tokens}")
    return groups


def _choice_dispatch(onehot, capacity, base_counts=None):
    """Per-choice capacity bookkeeping: position-ordered slots within
    each expert's queue, offset by `base_counts` (earlier choices'
    occupancy — GShard queues second choices AFTER all first choices).
    Returns (dispatch [T, E, C], counts [E])."""
    T, E = onehot.shape
    pos = jnp.cumsum(onehot, axis=0) * onehot               # [T, E]
    if base_counts is not None:
        pos = pos + base_counts[None, :] * onehot
    pos_in_expert = jnp.sum(pos, axis=-1) - 1.0             # [T]
    keep = pos_in_expert < capacity                         # [T]
    slot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                          dtype=jnp.float32)                # [T, C]
    dispatch = onehot[:, :, None] * slot[:, None, :] * \
        keep[:, None, None]                                 # [T, E, C]
    return dispatch, jnp.sum(onehot, axis=0)


def _one_hot_dispatch(gate_logits, capacity, top_k=1, rng=None,
                      jitter_eps=0.0):
    """Top-k capacity routing (GShard: k=2 is the paper default; k=1 is
    Switch).

    gate_logits [T, E] fp32 → (dispatch [T, E, C] bool-ish float,
    combine [T, E, C] float = normalized gate prob on the kept slot,
    aux_loss). With `rng` and `jitter_eps`, logits get GShard's
    multiplicative uniform jitter (training-time exploration).
    """
    T, E = gate_logits.shape
    if rng is not None and jitter_eps > 0.0:
        noise = jax.random.uniform(rng, gate_logits.shape,
                                   minval=1.0 - jitter_eps,
                                   maxval=1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)

    expert1 = jnp.argmax(probs, axis=-1)                    # [T]
    onehot1 = jax.nn.one_hot(expert1, E, dtype=jnp.float32)
    g1 = jnp.take_along_axis(probs, expert1[:, None], axis=-1)[:, 0]

    # GShard aux loss uses the FIRST choice's assignment statistics
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot1, axis=0)
    aux = E * jnp.sum(me * ce)

    dispatch1, counts1 = _choice_dispatch(onehot1, capacity)
    if top_k == 1:
        return dispatch1, dispatch1 * g1[:, None, None], aux

    if top_k != 2:
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    probs2 = probs * (1.0 - onehot1)                        # mask top-1
    expert2 = jnp.argmax(probs2, axis=-1)
    onehot2 = jax.nn.one_hot(expert2, E, dtype=jnp.float32)
    g2 = jnp.take_along_axis(probs, expert2[:, None], axis=-1)[:, 0]
    # normalize the pair (GShard combine weights)
    denom = g1 + g2 + 1e-9
    g1n, g2n = g1 / denom, g2 / denom
    dispatch2, _ = _choice_dispatch(onehot2, capacity,
                                    base_counts=counts1)
    dispatch = dispatch1 + dispatch2
    combine = dispatch1 * g1n[:, None, None] + \
        dispatch2 * g2n[:, None, None]
    return dispatch, combine, aux


def _expert_ffn(w_in, b_in, w_out, b_out, x):
    """One expert's FFN on [C, H] (weights [H, I]/[I, H])."""
    h = jax.nn.gelu(x @ w_in.astype(x.dtype) + b_in.astype(x.dtype))
    return h @ w_out.astype(x.dtype) + b_out.astype(x.dtype)


def _route_groups(gate, xg, capacity, top_k, rng, jitter_eps):
    """Route each group independently: xg [g, Tg, H] →
    (dispatch [g, Tg, E, C], combine [g, Tg, E, C], aux mean-over-groups).
    Dispatch/combine are cast to the compute dtype — dispatch is exactly
    0/1 (lossless); combine rounds like every other activation."""
    logits = (xg @ gate.astype(xg.dtype)).astype(jnp.float32)
    if rng is not None and jitter_eps > 0.0:
        route = jax.vmap(lambda lg, r: _one_hot_dispatch(
            lg, capacity, top_k=top_k, rng=r, jitter_eps=jitter_eps))
        dispatch, combine, aux = route(logits,
                                       jax.random.split(rng, xg.shape[0]))
    else:
        route = jax.vmap(lambda lg: _one_hot_dispatch(
            lg, capacity, top_k=top_k))
        dispatch, combine, aux = route(logits)
    return (dispatch.astype(xg.dtype), combine.astype(xg.dtype),
            jnp.mean(aux))


def moe_ffn_dense(params, x, capacity_factor=1.25, top_k=1, rng=None,
                  jitter_eps=0.0, groups=1):
    """Reference semantics on one device. params: stacked expert weights
    {"w_in" [E, H, I], "b_in" [E, I], "w_out" [E, I, H], "b_out" [E, H],
    "gate" [H, E]}; x [T, H] → (y [T, H], aux_loss). `groups` splits the
    tokens into independent routing groups (GShard's G dim) — capacity
    becomes per-group, dispatch memory drops by the group factor."""
    T, H = x.shape
    E = params["w_in"].shape[0]
    g = _resolve_groups(groups, T)
    tg = T // g
    capacity = max(1, int(capacity_factor * top_k * tg / E))
    xg = x.reshape(g, tg, H)
    dispatch, combine, aux = _route_groups(params["gate"], xg, capacity,
                                           top_k, rng, jitter_eps)

    expert_in = jnp.einsum("gtec,gth->egch", dispatch, xg)   # [E, g, C, H]
    expert_out = jax.vmap(_expert_ffn)(
        params["w_in"], params["b_in"], params["w_out"], params["b_out"],
        expert_in.reshape(E, g * capacity, H))              # [E, g*C, H]
    y = jnp.einsum("gtec,egch->gth", combine,
                   expert_out.reshape(E, g, capacity, H))
    return y.reshape(T, H), aux


def moe_ffn_expert_parallel(params, x, axis_name, ep, capacity_factor=1.25,
                            top_k=1, rng=None, jitter_eps=0.0, groups=1):
    """Inside shard_map: x is this rank's token shard [T_local, H];
    params carry this rank's experts ({"w_in" [E/ep, H, I], ...}) with
    the gate replicated. all_to_all exchanges expert-major token blocks
    so each rank runs only its own experts; a second all_to_all returns
    the outputs. Matches `moe_ffn_dense` run per-shard exactly (with the
    same `groups`: capacity is per local routing group)."""
    T, H = x.shape
    e_local = params["w_in"].shape[0]
    E = e_local * ep
    g = _resolve_groups(groups, T)
    tg = T // g
    capacity = max(1, int(capacity_factor * top_k * tg / E))
    if rng is not None:
        # decorrelate jitter across ranks: a replicated key would give
        # every rank's tokens identical noise (1/ep of the exploration)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
    xg = x.reshape(g, tg, H)
    dispatch, combine, aux = _route_groups(params["gate"], xg, capacity,
                                           top_k, rng, jitter_eps)

    # [g, Tg, E, C] → [E, g·C, H] expert-major buffers, then exchange:
    # split E = ep × e_local; all_to_all gives [ep, e_local, g·C, H]
    # where dim 0 is the source rank.
    expert_in = jnp.einsum("gtec,gth->egch", dispatch, xg)
    expert_in = expert_in.reshape(ep, e_local, g * capacity, H)
    expert_in = jax.lax.all_to_all(expert_in, axis_name, 0, 0,
                                   tiled=False)          # [ep, eL, g·C, H]

    flat_in = jnp.moveaxis(expert_in, 0, 1).reshape(
        e_local, ep * g * capacity, H)
    expert_out = jax.vmap(_expert_ffn)(
        params["w_in"], params["b_in"], params["w_out"], params["b_out"],
        flat_in)                                         # [eL, ep·g·C, H]
    expert_out = jnp.moveaxis(
        expert_out.reshape(e_local, ep, g * capacity, H), 1, 0)

    expert_out = jax.lax.all_to_all(expert_out, axis_name, 0, 0,
                                    tiled=False)         # [ep, eL, g·C, H]
    expert_out = expert_out.reshape(E, g, capacity, H)
    y = jnp.einsum("gtec,egch->gth", combine, expert_out)
    # aux is per-shard; average over the expert(-data) axis
    return y.reshape(T, H), jax.lax.pmean(aux, axis_name)


class MoELayer:
    """Engine-protocol MoE FFN layer (init/apply), expert-parallel when a
    mesh with an ``expert`` axis is supplied."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 capacity_factor=1.25, mesh=None, axis_name="expert",
                 param_dtype=jnp.float32, top_k=1, jitter_eps=0.0,
                 groups=1):
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.top_k = top_k          # 1 = Switch, 2 = GShard default
        self.jitter_eps = jitter_eps
        self.groups = groups        # 0 = auto (per-call token count)
        self.axis_name = axis_name
        self.ep = int(mesh.shape[axis_name]) \
            if mesh is not None and axis_name in mesh.axis_names else 1
        if num_experts % max(self.ep, 1) != 0:
            raise ValueError(f"num_experts {num_experts} must divide over "
                             f"expert-parallel size {self.ep}")
        self.param_dtype = param_dtype

    def init(self, rng, x=None):
        E, H, I = self.num_experts, self.hidden_size, self.intermediate_size
        k1, k2, k3 = jax.random.split(rng, 3)
        dt = self.param_dtype
        return {
            "gate": (jax.random.normal(k1, (H, E)) * 0.02).astype(dt),
            "w_in": (jax.random.normal(k2, (E, H, I)) * 0.02).astype(dt),
            "b_in": jnp.zeros((E, I), dt),
            "w_out": (jax.random.normal(k3, (E, I, H)) * 0.02).astype(dt),
            "b_out": jnp.zeros((E, H), dt),
        }

    def param_specs(self):
        """Expert dim sharded over the expert axis; gate replicated."""
        from jax.sharding import PartitionSpec as P
        ax = self.axis_name if self.ep > 1 else None
        return {"gate": P(), "w_in": P(ax), "b_in": P(ax),
                "w_out": P(ax), "b_out": P(ax)}

    def apply(self, params, x, rng=None):
        """x [..., H] → (y [..., H], aux_loss); dense or inside
        shard_map depending on construction."""
        lead = x.shape[:-1]
        flat = x.reshape(-1, self.hidden_size)
        kw = dict(capacity_factor=self.capacity_factor, top_k=self.top_k,
                  rng=rng, jitter_eps=self.jitter_eps if rng is not None
                  else 0.0, groups=self.groups)
        if self.ep > 1:
            y, aux = moe_ffn_expert_parallel(
                params, flat, self.axis_name, self.ep, **kw)
        else:
            y, aux = moe_ffn_dense(params, flat, **kw)
        return y.reshape(*lead, self.hidden_size), aux
