"""Mixture-of-Experts FFN with expert parallelism.

The reference (DeepSpeed v0.3.15) predates MoE; this layer exists so the
framework covers the modern 4th parallel axis alongside dp/pp/tp/sp. The
design is the GShard/Switch pattern, TPU-first:

- **top-1 gating with capacity**: each token routes to its argmax expert;
  an expert accepts at most `capacity` tokens (position-ordered).
  Overflow tokens combine to an exact-zero output — the surrounding
  transformer block's residual connection is what carries them through
  unchanged (standard Switch/GShard usage; this layer does NOT add the
  residual itself). Static shapes throughout.
- **two dispatch engines** (`dispatch=`):

  * ``"einsum"`` (default — the reference numerics): the dense GShard
    [T, E, C] one-hot dispatch/combine einsum pair, exactly the
    formulation GShard lowers to XLA. Pure MXU work, no scatter — but at
    top-2/cf=1.25 most of those flops multiply zeros.
  * ``"sort"``: argsort the routed token copies by expert (stable sort
    == GShard queue order: all first choices in token order, then all
    second choices), compact them into per-expert contiguous spans with
    capacity enforced by position-in-expert, run the expert FFN as ONE
    Pallas grouped matmul over the packed buffer
    (`ops/pallas/grouped_matmul.py` — ragged per-expert sizes, masked
    tails, XLA-fallback off-TPU), and combine by gathering each token's
    surviving rows with a weighted add. Same routing decisions, same
    capacity semantics, numerical parity with the einsum engine — at a
    fraction of the matmul flops.

- **token groups** (`groups`): GShard's G dimension. Tokens split into
  `g` independent routing groups with per-group capacity C/g, shrinking
  the dispatch/combine tensors from O(T·E·C) to O(T·E·C/g). `groups=1`
  is the exact ungrouped oracle; `groups=0` ("auto") picks the divisor
  of T whose group size is NEAREST `_AUTO_GROUP_TOKENS` (1024) and at
  least 128. The sort engine folds groups into E·g "virtual experts"
  (expert-major) so grouping costs nothing extra there.
- **expert parallelism**: experts shard over an ``expert`` mesh axis
  inside `shard_map`; token shards are exchanged with `all_to_all`
  (dispatch) and returned (combine), both riding ICI. With the sort
  engine, `a2a_overlap_chunks > 1` splits the exchange along the local-
  expert axis and software-pipelines `all_to_all(chunk i+1)` against
  expert-FFN(chunk i), hiding ICI time under MXU time (decorrelated
  jitter and the pmean'd aux loss are unchanged).
- Gate math in fp32; an auxiliary load-balancing loss (mean_prob ×
  mean_assignment per expert, scaled by E) is returned for the trainer.
- **top-2 combine weights**: `renorm_kept_choices=False` (default) keeps
  the GShard paper normalization — over the pair *before* capacity —
  which silently leaks the probability mass of an overflowed second
  choice. `True` renormalizes over the choices that actually survived
  capacity, so a token whose second choice overflowed carries full
  weight on its first. Off by default: the legacy einsum path stays
  bit-identical.

`moe_ffn_dense` is the single-device reference semantics;
`moe_ffn_expert_parallel` runs inside `shard_map` and matches it
exactly (tested on the 8-device mesh), for either engine.
"""

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp

# auto-grouping target: the largest per-group token count. 1024 keeps the
# per-layer dispatch+combine pair ≈ E·C·T·4B ≈ tens of MB at GPT scales
# while each group is still large enough for balanced routing statistics.
_AUTO_GROUP_TOKENS = 1024

DISPATCH_MODES = ("einsum", "sort")


class _RoutingStatsCollector:
    """Host-side sink for the sort engine's in-jit routing statistics
    (``moe.observability``): per-expert load fractions and the
    capacity-drop fraction land here via `jax.debug.callback`
    (unordered — the callback runs when the device values materialize,
    so the hot path never syncs) and the engine drains them into
    ``Train/MoE/*`` scalars at its step-record boundary.

    Samples are AVERAGED across everything that accumulated since the
    last drain: one entry per MoE layer per step, plus duplicates when
    rematerialization re-runs a layer's forward in the backward pass —
    duplicate values are identical, so the averages are unbiased."""

    # un-drained cap: with no monitor attached nothing ever drains —
    # keep the most recent window instead of growing forever
    MAX_PENDING = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._load = []          # [E] load fraction per emission
        self._drop = []          # capacity-drop fraction per emission

    def _record(self, load, drop):
        load = np.asarray(load, np.float64)
        drop = float(np.asarray(drop))
        with self._lock:
            self._load.append(load)
            self._drop.append(drop)
            if len(self._load) > self.MAX_PENDING:
                del self._load[:-self.MAX_PENDING]
                del self._drop[:-self.MAX_PENDING]

    def drain(self):
        """Averaged scalars since the last drain, or None when nothing
        was emitted (observability off, or the callbacks have not
        materialized yet)."""
        with self._lock:
            load, self._load = self._load, []
            drop, self._drop = self._drop, []
        if not load:
            return None
        mean_load = np.mean(np.stack(load), axis=0)     # [E]
        mean = float(mean_load.mean())
        return {
            "Train/MoE/expert_load_min": float(mean_load.min()),
            "Train/MoE/expert_load_max": float(mean_load.max()),
            # coefficient of variation: 0 = perfectly balanced; the
            # single-number imbalance series worth alerting on
            "Train/MoE/expert_load_cv":
                float(mean_load.std() / max(mean, 1e-12)),
            "Train/MoE/capacity_drop_fraction": float(np.mean(drop)),
        }


ROUTING_STATS = _RoutingStatsCollector()


def _emit_routing_stats(route, capacity, E, g):
    """Emit one routing observation from inside the compiled step (sort
    engine only — `route.counts`/`route.pos` already hold the
    position-in-expert bookkeeping, so the stats cost two reductions).
    The virtual-expert counts fold back to real experts
    (virtual id = expert·g + group)."""
    kT = route.pos.shape[0]                      # routed copies (T·top_k)
    counts_e = route.counts.reshape(E, g).sum(axis=1)
    load = counts_e.astype(jnp.float32) / max(kT, 1)
    kept = jnp.sum(jnp.minimum(route.counts, capacity))
    drop = 1.0 - kept.astype(jnp.float32) / max(kT, 1)
    jax.debug.callback(ROUTING_STATS._record, load, drop, ordered=False)


@functools.lru_cache(maxsize=None)
def _resolve_groups(groups, tokens):
    """0/'auto' → the divisor of `tokens` whose group size is nearest
    `_AUTO_GROUP_TOKENS` (never below 128: a token count with only tiny
    divisors near the target — e.g. 2·1031 — would otherwise shrink
    capacity to ~1 and silently drop routed tokens); otherwise validate
    the explicit count. Memoized per (groups, tokens): the O(√T) divisor
    search used to run on every trace."""
    if groups in (0, None, "auto"):
        best_g, best_cost = 1, abs(tokens - _AUTO_GROUP_TOKENS)
        d = 1
        while d * d <= tokens:
            if tokens % d == 0:
                for g in (d, tokens // d):
                    size = tokens // g
                    if size < 128:
                        continue
                    cost = abs(size - _AUTO_GROUP_TOKENS)
                    if cost < best_cost or (cost == best_cost
                                            and g > best_g):
                        best_g, best_cost = g, cost
            d += 1
        return best_g
    groups = int(groups)
    if groups < 1 or tokens % groups:
        raise ValueError(f"groups={groups} must be ≥1 and divide the "
                         f"token count {tokens}")
    return groups


def _choice_dispatch(onehot, capacity, base_counts=None):
    """Per-choice capacity bookkeeping: position-ordered slots within
    each expert's queue, offset by `base_counts` (earlier choices'
    occupancy — GShard queues second choices AFTER all first choices).
    Returns (dispatch [T, E, C], counts [E])."""
    T, E = onehot.shape
    pos = jnp.cumsum(onehot, axis=0) * onehot               # [T, E]
    if base_counts is not None:
        pos = pos + base_counts[None, :] * onehot
    pos_in_expert = jnp.sum(pos, axis=-1) - 1.0             # [T]
    keep = pos_in_expert < capacity                         # [T]
    slot = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                          dtype=jnp.float32)                # [T, C]
    dispatch = onehot[:, :, None] * slot[:, None, :] * \
        keep[:, None, None]                                 # [T, E, C]
    return dispatch, jnp.sum(onehot, axis=0)


def _one_hot_dispatch(gate_logits, capacity, top_k=1, rng=None,
                      jitter_eps=0.0, renorm_kept_choices=False):
    """Top-k capacity routing (GShard: k=2 is the paper default; k=1 is
    Switch).

    gate_logits [T, E] fp32 → (dispatch [T, E, C] bool-ish float,
    combine [T, E, C] float = normalized gate prob on the kept slot,
    aux_loss). With `rng` and `jitter_eps`, logits get GShard's
    multiplicative uniform jitter (training-time exploration).
    `renorm_kept_choices` normalizes the top-2 pair over the choices
    that SURVIVED capacity instead of the pre-capacity pair (see module
    docstring); False keeps the legacy math bit-identical.
    """
    T, E = gate_logits.shape
    if rng is not None and jitter_eps > 0.0:
        noise = jax.random.uniform(rng, gate_logits.shape,
                                   minval=1.0 - jitter_eps,
                                   maxval=1.0 + jitter_eps)
        gate_logits = gate_logits * noise
    probs = jax.nn.softmax(gate_logits, axis=-1)

    expert1 = jnp.argmax(probs, axis=-1)                    # [T]
    onehot1 = jax.nn.one_hot(expert1, E, dtype=jnp.float32)
    g1 = jnp.take_along_axis(probs, expert1[:, None], axis=-1)[:, 0]

    # GShard aux loss uses the FIRST choice's assignment statistics
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot1, axis=0)
    aux = E * jnp.sum(me * ce)

    dispatch1, counts1 = _choice_dispatch(onehot1, capacity)
    if top_k == 1:
        return dispatch1, dispatch1 * g1[:, None, None], aux

    if top_k != 2:
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    probs2 = probs * (1.0 - onehot1)                        # mask top-1
    expert2 = jnp.argmax(probs2, axis=-1)
    onehot2 = jax.nn.one_hot(expert2, E, dtype=jnp.float32)
    g2 = jnp.take_along_axis(probs, expert2[:, None], axis=-1)[:, 0]
    dispatch2, _ = _choice_dispatch(onehot2, capacity,
                                    base_counts=counts1)
    if renorm_kept_choices:
        # normalize over the kept pair: an overflowed second choice's
        # mass moves to the surviving first choice instead of leaking
        kept1 = jnp.sum(dispatch1, axis=(1, 2))             # 1.0 or 0.0
        kept2 = jnp.sum(dispatch2, axis=(1, 2))
        w1, w2 = g1 * kept1, g2 * kept2
        denom = w1 + w2 + 1e-9
        g1n, g2n = w1 / denom, w2 / denom
    else:
        # normalize the pair (GShard combine weights)
        denom = g1 + g2 + 1e-9
        g1n, g2n = g1 / denom, g2 / denom
    dispatch = dispatch1 + dispatch2
    combine = dispatch1 * g1n[:, None, None] + \
        dispatch2 * g2n[:, None, None]
    return dispatch, combine, aux


def _expert_ffn(w_in, b_in, w_out, b_out, x):
    """One expert's FFN on [C, H] (weights [H, I]/[I, H])."""
    h = jax.nn.gelu(x @ w_in.astype(x.dtype) + b_in.astype(x.dtype))
    return h @ w_out.astype(x.dtype) + b_out.astype(x.dtype)


def _route_groups(gate, xg, capacity, top_k, rng, jitter_eps,
                  renorm_kept_choices=False):
    """Route each group independently: xg [g, Tg, H] →
    (dispatch [g, Tg, E, C], combine [g, Tg, E, C], aux mean-over-groups).
    Dispatch/combine are cast to the compute dtype — dispatch is exactly
    0/1 (lossless); combine rounds like every other activation."""
    logits = (xg @ gate.astype(xg.dtype)).astype(jnp.float32)
    if rng is not None and jitter_eps > 0.0:
        route = jax.vmap(lambda lg, r: _one_hot_dispatch(
            lg, capacity, top_k=top_k, rng=r, jitter_eps=jitter_eps,
            renorm_kept_choices=renorm_kept_choices))
        dispatch, combine, aux = route(logits,
                                       jax.random.split(rng, xg.shape[0]))
    else:
        route = jax.vmap(lambda lg: _one_hot_dispatch(
            lg, capacity, top_k=top_k,
            renorm_kept_choices=renorm_kept_choices))
        dispatch, combine, aux = route(logits)
    return (dispatch.astype(xg.dtype), combine.astype(xg.dtype),
            jnp.mean(aux))


# ---------------------------------------------------------------------------
# sort-based dispatch engine
# ---------------------------------------------------------------------------

class _SortRoute:
    """Routing plan over V = E·g virtual experts (expert-major:
    v = expert·g + group). Copy-major arrays are [k·T]: copy c < T is
    token c's first choice, copy c ≥ T its second."""

    def __init__(self, experts_v, pos, weights, counts, starts, order,
                 aux):
        self.experts_v = experts_v   # [kT] virtual expert per copy
        self.pos = pos               # [kT] position-in-expert per copy
        self.weights = weights       # tuple of [T] combine weights
        self.counts = counts         # [V] routed copies per virtual expert
        self.starts = starts         # [V] exclusive prefix of counts
        self.order = order           # [kT] stable sort permutation
        self.aux = aux


def _jittered_probs(gate, xg, rng, jitter_eps):
    """Gate probabilities [g, Tg, E] with the SAME per-group jitter
    construction as the einsum engine (vmapped per-group key split) —
    the two dispatch engines must draw identical noise so they route
    identically."""
    logits = (xg @ gate.astype(xg.dtype)).astype(jnp.float32)
    if rng is not None and jitter_eps > 0.0:
        keys = jax.random.split(rng, xg.shape[0])
        noise = jax.vmap(lambda r: jax.random.uniform(
            r, logits.shape[1:], minval=1.0 - jitter_eps,
            maxval=1.0 + jitter_eps))(keys)
        logits = logits * noise
    return jax.nn.softmax(logits, axis=-1)


def _sort_route(probs, capacity, top_k, renorm_kept_choices):
    """probs [g, Tg, E] fp32 → _SortRoute.

    The stable argsort over (virtual-)expert ids reproduces the GShard
    queue exactly: copies are enumerated choice-major (all first choices
    in token order, then all second choices), so within each expert the
    sorted order is first-choices-then-second-choices — identical
    position-in-expert bookkeeping to `_choice_dispatch`'s cumsum +
    base_counts offset, without the [T, E, C] one-hot tensors."""
    g, tg, E = probs.shape
    T = g * tg
    p2 = probs.reshape(T, E)
    gi = (jnp.arange(T, dtype=jnp.int32) // tg)

    expert1 = jnp.argmax(p2, axis=-1)
    onehot1 = jax.nn.one_hot(expert1, E, dtype=jnp.float32)
    g1 = jnp.take_along_axis(p2, expert1[:, None], axis=-1)[:, 0]
    # GShard aux loss, per group then averaged (matches _route_groups)
    me = jnp.mean(probs, axis=1)                            # [g, E]
    ce = jnp.mean(onehot1.reshape(g, tg, E), axis=1)        # [g, E]
    aux = jnp.mean(E * jnp.sum(me * ce, axis=-1))

    v1 = expert1.astype(jnp.int32) * g + gi
    if top_k == 1:
        experts_v = v1
        gates = (g1,)
    elif top_k == 2:
        probs2 = p2 * (1.0 - onehot1)                       # mask top-1
        expert2 = jnp.argmax(probs2, axis=-1)
        g2 = jnp.take_along_axis(p2, expert2[:, None], axis=-1)[:, 0]
        experts_v = jnp.concatenate([v1, expert2.astype(jnp.int32) * g + gi])
        gates = (g1, g2)
    else:
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")

    kT = experts_v.shape[0]
    V = E * g
    order = jnp.argsort(experts_v)          # stable → GShard queue order
    counts = jnp.zeros((V,), jnp.int32).at[experts_v].add(1)
    starts = jnp.cumsum(counts) - counts    # exclusive prefix, no concat
    pos_sorted = jnp.arange(kT, dtype=jnp.int32) - starts[experts_v[order]]
    pos = jnp.zeros((kT,), jnp.int32).at[order].set(pos_sorted)
    kept = pos < capacity

    k1 = kept[:T].astype(jnp.float32)
    if top_k == 1:
        weights = (gates[0] * k1,)
    else:
        k2 = kept[T:].astype(jnp.float32)
        g1, g2 = gates
        if renorm_kept_choices:
            w1, w2 = g1 * k1, g2 * k2
            denom = w1 + w2 + 1e-9
            weights = (w1 / denom, w2 / denom)
        else:
            denom = g1 + g2 + 1e-9
            weights = (g1 / denom * k1, g2 / denom * k2)
    return _SortRoute(experts_v, pos, weights, counts, starts, order, aux)


def _pick_span(capacity, block_m=None):
    from ..ops.pallas.grouped_matmul import pick_span
    return pick_span(capacity, block_m)


def _fill_buffer(x, route, capacity, span):
    """Compact routed copies into the [V·span, H] expert-major buffer by
    GATHER: buffer row (v, p) holds the p-th surviving copy of virtual
    expert v (sorted order), zero when p ≥ min(count, capacity). Returns
    (buffer, group_sizes [V])."""
    T, H = x.shape
    kT = route.order.shape[0]
    V = route.counts.shape[0]
    tok_sorted = route.order % T            # sorted copy → source token
    p = jnp.arange(span, dtype=jnp.int32)
    src = route.starts[:, None] + p[None, :]                # [V, span]
    sizes = jnp.minimum(route.counts, capacity)
    valid = p[None, :] < sizes[:, None]
    tok = tok_sorted[jnp.clip(src, 0, kT - 1)]
    buf = jnp.where(valid[..., None], x[tok], 0)            # [V, span, H]
    return buf.reshape(V * span, H), sizes


def _sort_ffn(params, buf, sizes, span, lut, n_w, rows_per_w, block_m,
              block_n, backend, ffn_quant=None):
    """Expert FFN over the packed buffer as two grouped matmuls. Biases
    ride a [n_w, rows, ·] reshape (no per-row gather). Masked tail rows
    come out of the second matmul as exact zeros plus a bias term; the
    combine never gathers them.

    `ffn_quant` = (recipe, margin, amax_row [4, H]) runs both grouped
    matmuls with delayed-scaling fake-quantized operands
    (`ops.pallas.quant_matmul.grouped_scaled_operands`) and makes the
    return (out, new_amax_row)."""
    from ..ops.pallas.grouped_matmul import grouped_matmul
    dt = buf.dtype
    w_in = params["w_in"].astype(dt)
    b_in = params["b_in"].astype(dt)
    w_out = params["w_out"].astype(dt)
    b_out = params["b_out"].astype(dt)
    inter = w_in.shape[-1]
    new_row = None
    if ffn_quant is not None:
        from ..ops.pallas.quant_matmul import grouped_scaled_operands
        recipe, margin, amax_row = ffn_quant
        buf, w_in, hx_in, hw_in = grouped_scaled_operands(
            buf, w_in, amax_row[0], amax_row[1], recipe, margin)
    h = grouped_matmul(buf, w_in, sizes, span, lut, block_m, block_n,
                       backend)
    h = jax.nn.gelu(h.reshape(n_w, rows_per_w, inter) + b_in[:, None, :])
    h = h.reshape(-1, inter)
    if ffn_quant is not None:
        from ..ops.pallas.quant_matmul import grouped_scaled_operands
        h, w_out, hx_out, hw_out = grouped_scaled_operands(
            h, w_out, amax_row[2], amax_row[3], recipe, margin)
        new_row = jnp.stack([hx_in, hw_in, hx_out, hw_out])
    out = grouped_matmul(h, w_out, sizes, span, lut,
                         block_m, block_n, backend)
    hidden = w_out.shape[-1]
    out = out.reshape(n_w, rows_per_w, hidden) + b_out[:, None, :]
    out = out.reshape(-1, hidden)
    if ffn_quant is not None:
        return out, new_row
    return out


def _sort_combine(out_buf, route, span, T, dtype):
    """y[t] = Σ_k weight_k[t] · out_buf[row of copy k] — the gather +
    weighted-add replacement for the [T, E, C] combine einsum. Dropped
    copies carry weight 0 (their clipped row gather is a no-op)."""
    R = out_buf.shape[0]
    rows = jnp.clip(route.experts_v * span + route.pos, 0, R - 1)
    y = None
    for c, wk in enumerate(route.weights):
        term = wk.astype(dtype)[:, None] * out_buf[rows[c * T:(c + 1) * T]]
        y = term if y is None else y + term
    return y


def _gmm_geometry(capacity, k_dim, n_dim, dtype, block_m, block_n,
                  backend):
    """Resolve (span, block_m, block_n) — autotuned on TPU when the
    Pallas backend is in play, static defaults otherwise."""
    if (block_m is None or block_n is None) and backend != "xla":
        from ..ops.autotune import grouped_matmul_blocks
        from ..ops.pallas.grouped_matmul import _interpret
        if not _interpret():
            bm, bn = grouped_matmul_blocks(capacity, k_dim, n_dim, dtype)
            block_m = block_m or bm
            block_n = block_n or bn
    span, bm = _pick_span(capacity, block_m)
    return span, bm, block_n


def moe_ffn_dense(params, x, capacity_factor=1.25, top_k=1, rng=None,
                  jitter_eps=0.0, groups=1, dispatch="einsum",
                  renorm_kept_choices=False, gmm_block_m=None,
                  gmm_block_n=None, gmm_backend=None, observe=False,
                  ffn_quant=None):
    """Reference semantics on one device. params: stacked expert weights
    {"w_in" [E, H, I], "b_in" [E, I], "w_out" [E, I, H], "b_out" [E, H],
    "gate" [H, E]}; x [T, H] → (y [T, H], aux_loss). `groups` splits the
    tokens into independent routing groups (GShard's G dim) — capacity
    becomes per-group, dispatch memory drops by the group factor.
    `dispatch` picks the engine (module docstring); both route
    identically."""
    if dispatch not in DISPATCH_MODES:
        raise ValueError(f"dispatch must be one of {DISPATCH_MODES}, "
                         f"got {dispatch!r}")
    if observe and dispatch != "sort":
        raise ValueError(
            "observe=True requires dispatch='sort': the routing stats "
            "come from the sort engine's position-in-expert bookkeeping")
    if ffn_quant is not None and dispatch != "sort":
        raise ValueError(
            "quantization.ffn on MoE blocks requires dispatch='sort': "
            "the delayed-scaling path quantizes the grouped expert "
            "matmul operands (the einsum engine spends its flops on the "
            "one-hot dispatch tensor, which quantization cannot help)")
    T, H = x.shape
    E = params["w_in"].shape[0]
    g = _resolve_groups(groups, T)
    tg = T // g
    capacity = max(1, int(capacity_factor * top_k * tg / E))
    xg = x.reshape(g, tg, H)

    if dispatch == "einsum":
        dispatch_t, combine, aux = _route_groups(
            params["gate"], xg, capacity, top_k, rng, jitter_eps,
            renorm_kept_choices=renorm_kept_choices)
        expert_in = jnp.einsum("gtec,gth->egch", dispatch_t, xg)
        expert_out = jax.vmap(_expert_ffn)(
            params["w_in"], params["b_in"], params["w_out"],
            params["b_out"],
            expert_in.reshape(E, g * capacity, H))          # [E, g*C, H]
        y = jnp.einsum("gtec,egch->gth", combine,
                       expert_out.reshape(E, g, capacity, H))
        return y.reshape(T, H), aux

    probs = _jittered_probs(params["gate"], xg, rng, jitter_eps)
    route = _sort_route(probs, capacity, top_k, renorm_kept_choices)
    if observe:
        _emit_routing_stats(route, capacity, E, g)
    span, bm, bn = _gmm_geometry(capacity, H, params["w_in"].shape[-1],
                                 x.dtype, gmm_block_m, gmm_block_n,
                                 gmm_backend)
    buf, sizes = _fill_buffer(x, route, capacity, span)
    lut = tuple(np.repeat(np.arange(E), g))
    out_buf = _sort_ffn(params, buf, sizes, span, lut, E, g * span,
                        bm, bn, gmm_backend, ffn_quant=ffn_quant)
    if ffn_quant is not None:
        out_buf, new_amax_row = out_buf
        return (_sort_combine(out_buf, route, span, T, x.dtype),
                route.aux, new_amax_row)
    return _sort_combine(out_buf, route, span, T, x.dtype), route.aux


def _a2a(t, axis_name):
    return jax.lax.all_to_all(t, axis_name, 0, 0, tiled=False)


def _overlap_chunks(requested, e_local):
    """Largest divisor of the local expert count ≤ the requested chunk
    count (1 = no pipelining)."""
    n = max(1, min(int(requested), e_local))
    while e_local % n:
        n -= 1
    return n


def moe_ffn_expert_parallel(params, x, axis_name, ep, capacity_factor=1.25,
                            top_k=1, rng=None, jitter_eps=0.0, groups=1,
                            dispatch="einsum", renorm_kept_choices=False,
                            a2a_overlap_chunks=1, gmm_block_m=None,
                            gmm_block_n=None, gmm_backend=None,
                            observe=False):
    """Inside shard_map: x is this rank's token shard [T_local, H];
    params carry this rank's experts ({"w_in" [E/ep, H, I], ...}) with
    the gate replicated. all_to_all exchanges expert-major token blocks
    so each rank runs only its own experts; a second all_to_all returns
    the outputs. Matches `moe_ffn_dense` run per-shard exactly (with the
    same `groups`: capacity is per local routing group).

    With `dispatch="sort"` and `a2a_overlap_chunks > 1` the exchange is
    chunked along the local-expert axis and software-pipelined: the
    all_to_all for chunk i+1 is issued before the expert FFN of chunk i,
    so XLA's scheduler can hide the ICI transfer under the grouped
    matmul. Results are bit-identical to the unchunked exchange (pure
    reordering of independent slices)."""
    if dispatch not in DISPATCH_MODES:
        raise ValueError(f"dispatch must be one of {DISPATCH_MODES}, "
                         f"got {dispatch!r}")
    if observe and dispatch != "sort":
        raise ValueError(
            "observe=True requires dispatch='sort': the routing stats "
            "come from the sort engine's position-in-expert bookkeeping")
    T, H = x.shape
    e_local = params["w_in"].shape[0]
    E = e_local * ep
    g = _resolve_groups(groups, T)
    tg = T // g
    capacity = max(1, int(capacity_factor * top_k * tg / E))
    if rng is not None:
        # decorrelate jitter across ranks: a replicated key would give
        # every rank's tokens identical noise (1/ep of the exploration)
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
    xg = x.reshape(g, tg, H)

    if dispatch == "einsum":
        dispatch_t, combine, aux = _route_groups(
            params["gate"], xg, capacity, top_k, rng, jitter_eps,
            renorm_kept_choices=renorm_kept_choices)

        # [g, Tg, E, C] → [E, g·C, H] expert-major buffers, then exchange:
        # split E = ep × e_local; all_to_all gives [ep, e_local, g·C, H]
        # where dim 0 is the source rank.
        expert_in = jnp.einsum("gtec,gth->egch", dispatch_t, xg)
        expert_in = expert_in.reshape(ep, e_local, g * capacity, H)
        expert_in = _a2a(expert_in, axis_name)           # [ep, eL, g·C, H]

        flat_in = jnp.moveaxis(expert_in, 0, 1).reshape(
            e_local, ep * g * capacity, H)
        expert_out = jax.vmap(_expert_ffn)(
            params["w_in"], params["b_in"], params["w_out"],
            params["b_out"], flat_in)                    # [eL, ep·g·C, H]
        expert_out = jnp.moveaxis(
            expert_out.reshape(e_local, ep, g * capacity, H), 1, 0)

        expert_out = _a2a(expert_out, axis_name)         # [ep, eL, g·C, H]
        expert_out = expert_out.reshape(E, g, capacity, H)
        y = jnp.einsum("gtec,egch->gth", combine, expert_out)
        # aux is per-shard; average over the expert(-data) axis
        return y.reshape(T, H), jax.lax.pmean(aux, axis_name)

    # ---- sort engine -----------------------------------------------------
    probs = _jittered_probs(params["gate"], xg, rng, jitter_eps)
    route = _sort_route(probs, capacity, top_k, renorm_kept_choices)
    if observe:
        # per-rank stats over this rank's token shard (each rank routes
        # its own tokens to all E global experts); the host collector
        # averages across ranks' emissions
        _emit_routing_stats(route, capacity, E, g)
    span, bm, bn = _gmm_geometry(capacity, H, params["w_in"].shape[-1],
                                 x.dtype, gmm_block_m, gmm_block_n,
                                 gmm_backend)
    buf, sizes = _fill_buffer(x, route, capacity, span)  # [E·g·span, H]

    n_ch = _overlap_chunks(a2a_overlap_chunks, e_local)
    e_chunk = e_local // n_ch
    send = buf.reshape(ep, e_local, g * span, H)
    sz_send = sizes.reshape(ep, e_local, g)

    def ffn_chunk(ci, rbuf, rsz):
        # rbuf [ep, e_chunk, g·span, H] (dim 0 = source rank); the
        # span layout makes the received sizes the RAGGED group sizes
        # the kernel was built for — ep·g spans per local expert.
        flat = jnp.moveaxis(rbuf, 0, 1).reshape(
            e_chunk * ep * g * span, H)
        fsz = jnp.moveaxis(rsz, 0, 1).reshape(e_chunk * ep * g)
        lut = tuple(np.repeat(np.arange(e_chunk), ep * g))
        sl = slice(ci * e_chunk, (ci + 1) * e_chunk)
        pchunk = {k: params[k][sl]
                  for k in ("w_in", "b_in", "w_out", "b_out")}
        out = _sort_ffn(pchunk, flat, fsz, span, lut, e_chunk,
                        ep * g * span, bm, bn, gmm_backend)
        return jnp.moveaxis(out.reshape(e_chunk, ep, g * span, H), 1, 0)

    chunk = lambda t, ci: t[:, ci * e_chunk:(ci + 1) * e_chunk]  # noqa: E731
    # software pipeline: exchange chunk i+1 concurrently with FFN(i)
    recv = [(_a2a(chunk(send, 0), axis_name),
             _a2a(chunk(sz_send, 0), axis_name))]
    outs = []
    for ci in range(n_ch):
        if ci + 1 < n_ch:
            recv.append((_a2a(chunk(send, ci + 1), axis_name),
                         _a2a(chunk(sz_send, ci + 1), axis_name)))
        rbuf, rsz = recv[ci]
        outs.append(_a2a(ffn_chunk(ci, rbuf, rsz), axis_name))
    out_full = outs[0] if n_ch == 1 else jnp.concatenate(outs, axis=1)
    out_buf = out_full.reshape(E * g * span, H)
    y = _sort_combine(out_buf, route, span, T, x.dtype)
    return y, jax.lax.pmean(route.aux, axis_name)


class MoELayer:
    """Engine-protocol MoE FFN layer (init/apply), expert-parallel when a
    mesh with an ``expert`` axis is supplied."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 capacity_factor=1.25, mesh=None, axis_name="expert",
                 param_dtype=jnp.float32, top_k=1, jitter_eps=0.0,
                 groups=1, dispatch="einsum", renorm_kept_choices=False,
                 a2a_overlap_chunks=1):
        if dispatch not in DISPATCH_MODES:
            raise ValueError(f"dispatch must be one of {DISPATCH_MODES}, "
                             f"got {dispatch!r}")
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.top_k = top_k          # 1 = Switch, 2 = GShard default
        self.jitter_eps = jitter_eps
        self.groups = groups        # 0 = auto (per-call token count)
        self.dispatch = dispatch
        self.renorm_kept_choices = renorm_kept_choices
        self.a2a_overlap_chunks = a2a_overlap_chunks
        self.axis_name = axis_name
        self.ep = int(mesh.shape[axis_name]) \
            if mesh is not None and axis_name in mesh.axis_names else 1
        if num_experts % max(self.ep, 1) != 0:
            raise ValueError(f"num_experts {num_experts} must divide over "
                             f"expert-parallel size {self.ep}")
        self.param_dtype = param_dtype

    def init(self, rng, x=None):
        E, H, I = self.num_experts, self.hidden_size, self.intermediate_size
        k1, k2, k3 = jax.random.split(rng, 3)
        dt = self.param_dtype
        return {
            "gate": (jax.random.normal(k1, (H, E)) * 0.02).astype(dt),
            "w_in": (jax.random.normal(k2, (E, H, I)) * 0.02).astype(dt),
            "b_in": jnp.zeros((E, I), dt),
            "w_out": (jax.random.normal(k3, (E, I, H)) * 0.02).astype(dt),
            "b_out": jnp.zeros((E, H), dt),
        }

    def param_specs(self):
        """Expert dim sharded over the expert axis; gate replicated."""
        from jax.sharding import PartitionSpec as P
        ax = self.axis_name if self.ep > 1 else None
        return {"gate": P(), "w_in": P(ax), "b_in": P(ax),
                "w_out": P(ax), "b_out": P(ax)}

    def apply(self, params, x, rng=None):
        """x [..., H] → (y [..., H], aux_loss); dense or inside
        shard_map depending on construction."""
        lead = x.shape[:-1]
        flat = x.reshape(-1, self.hidden_size)
        kw = dict(capacity_factor=self.capacity_factor, top_k=self.top_k,
                  rng=rng, jitter_eps=self.jitter_eps if rng is not None
                  else 0.0, groups=self.groups, dispatch=self.dispatch,
                  renorm_kept_choices=self.renorm_kept_choices)
        if self.ep > 1:
            y, aux = moe_ffn_expert_parallel(
                params, flat, self.axis_name, self.ep,
                a2a_overlap_chunks=self.a2a_overlap_chunks, **kw)
        else:
            y, aux = moe_ffn_dense(params, flat, **kw)
        return y.reshape(*lead, self.hidden_size), aux
