"""Shims over jax API renames (and version-specific miscompiles) so the
framework runs on every jax the fleet actually has installed.

Two symbols moved between the jax versions we support:

- ``shard_map``: promoted from ``jax.experimental.shard_map`` to
  top-level ``jax.shard_map`` (jax 0.6).
- Pallas TPU compiler params: ``pltpu.TPUCompilerParams`` renamed to
  ``pltpu.CompilerParams`` (jax 0.5).

Import both from here; never from jax directly.

One workaround for a jax 0.4.37 GSPMD bug lives here too: ``pad_tail``
(see its docstring) — use it instead of ``jnp.concatenate`` whenever a
possibly-sharded array gets a constant tail appended.
"""

import functools
import inspect

try:
    from jax import shard_map as _shard_map          # jax >= 0.6
except ImportError:                      # pragma: no cover - version dep
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    # older jax spells the replication check `check_rep`
    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

from jax.experimental.pallas import tpu as _pltpu

# jax >= 0.5 spelling first; fall back to the long-stable old name.
CompilerParams = getattr(_pltpu, "CompilerParams", None) or \
    _pltpu.TPUCompilerParams


def pad_tail(x, n_pad, value):
    """Append ``n_pad`` rows of ``value`` along axis 0 — via ``jnp.pad``,
    NEVER ``jnp.concatenate``.

    jax 0.4.37's SPMD partitioner miscompiles
    ``concatenate([reshape(slice(sharded)), replicated_fill])``: the
    sharded operand is read back with a strided/garbled element order, so
    the padded array's REAL values are wrong (measured on the CPU backend
    with a ``data``-sharded [B, S] batch: element i comes back as 2i).
    The ``pad`` HLO lowers correctly on every jax we support. This bug
    corrupted the fused LM-head loss labels on any multi-axis mesh — the
    TP/SP trajectory-parity failures tracked since PR 1 were exactly this.
    """
    import jax.numpy as jnp
    if n_pad == 0:
        return x
    widths = [(0, n_pad)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths, constant_values=value)
