"""Shims over jax API renames so the framework runs on every jax the
fleet actually has installed.

Two symbols moved between the jax versions we support:

- ``shard_map``: promoted from ``jax.experimental.shard_map`` to
  top-level ``jax.shard_map`` (jax 0.6).
- Pallas TPU compiler params: ``pltpu.TPUCompilerParams`` renamed to
  ``pltpu.CompilerParams`` (jax 0.5).

Import both from here; never from jax directly.
"""

import functools
import inspect

try:
    from jax import shard_map as _shard_map          # jax >= 0.6
except ImportError:                      # pragma: no cover - version dep
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    # older jax spells the replication check `check_rep`
    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

from jax.experimental.pallas import tpu as _pltpu

# jax >= 0.5 spelling first; fall back to the long-stable old name.
CompilerParams = getattr(_pltpu, "CompilerParams", None) or \
    _pltpu.TPUCompilerParams
