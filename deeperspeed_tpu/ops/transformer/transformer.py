"""Fused BERT-style transformer layer (reference:
`deepspeed/ops/transformer/transformer.py:39,470` over ~7k LoC of CUDA in
`csrc/transformer/`).

The reference hand-fuses QKV strided-batch GEMMs, masked softmax,
bias+gelu, bias+dropout+residual and layernorm into CUDA kernels. On TPU
the same fusion set is achieved with (a) XLA fusing elementwise chains into
the surrounding matmuls automatically and (b) the Pallas flash-attention
kernel for the softmax·V core. The memory-saving config knobs map to remat:

- ``normalize_invertible``  → remat the whole block (drops inputs).
- ``gelu_checkpoint``       → remat the FFN span.
- ``attn_dropout_checkpoint`` → remat the attention span.
- ``stochastic_mode``       → accepted (bf16 on TPU already gives the
  throughput the reference's stochastic rounding chased).

`DeepSpeedTransformerLayer` follows the framework layer protocol
(init/apply) so it can be listed in a `PipelineModule` or injected by
`module_inject.replace_transformer_layer`.
"""

import math

import jax
import jax.numpy as jnp

from ..pallas.flash_attention import (flash_attention,
                                      flash_attention_kbias,
                                      flash_attention_supported,
                                      flash_attention_train)


class TransformerConfig:
    def __init__(self, batch_size=-1, hidden_size=-1, intermediate_size=-1,
                 heads=-1, attn_dropout_ratio=-1, hidden_dropout_ratio=-1,
                 num_hidden_layers=-1, initializer_range=-1):
        self.layer_id = -1
        self.batch_size = batch_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.heads = heads
        self.attn_dropout_ratio = attn_dropout_ratio
        self.hidden_dropout_ratio = hidden_dropout_ratio
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range


class DeepSpeedTransformerConfig(TransformerConfig):
    """Config-compatible with the reference (same fields/defaults)."""

    def __init__(self, batch_size=-1, hidden_size=-1, intermediate_size=-1,
                 heads=-1, attn_dropout_ratio=-1, hidden_dropout_ratio=-1,
                 num_hidden_layers=-1, initializer_range=-1,
                 layer_norm_eps=1e-12, local_rank=-1, seed=-1, fp16=False,
                 pre_layer_norm=True, normalize_invertible=False,
                 gelu_checkpoint=False, adjust_init_range=True,
                 attn_dropout_checkpoint=False, stochastic_mode=False,
                 huggingface=False, training=True):
        super().__init__(
            batch_size, hidden_size,
            intermediate_size if intermediate_size > 0 else 4 * hidden_size,
            heads, attn_dropout_ratio, hidden_dropout_ratio,
            num_hidden_layers, initializer_range)
        self.fp16 = fp16
        self.pre_layer_norm = pre_layer_norm
        self.local_rank = local_rank
        self.seed = seed
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.adjust_init_range = adjust_init_range
        self.test_gemm = False
        self.layer_norm_eps = layer_norm_eps
        self.training = training
        self.is_grad_enabled = True
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.stochastic_mode = stochastic_mode
        self.huggingface = huggingface

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            setattr(config, key, value)
        return config

    @classmethod
    def from_json_file(cls, json_file):
        import json
        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))


def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    return ((x32 - mean) * jax.lax.rsqrt(var + eps) *
            scale.astype(jnp.float32) +
            bias.astype(jnp.float32)).astype(x.dtype)


def _flash_min_seq():
    """Shortest sequence the fused flash kernels take over the
    materialized-[B,H,S,S] XLA path. At short S the score tensor is
    small and XLA's fused einsum+softmax beats the kernel's per-instance
    fixed costs (measured on v5e BERT-Large seq128 train: 45.9% vs
    39.1% MFU); at long S flash's O(S) memory wins. Tunable like the
    reference's gemm algo selection (`csrc/includes/gemm_test.h`)."""
    import os
    return int(os.environ.get("DS_FLASH_MIN_SEQ", "256"))


def _dropout(x, rate, rng, deterministic):
    """Hash-mask dropout: one scalar threefry draw seeds an int32
    avalanche hash over element indices (the reference generates masks
    with curand Philox inside its kernels, `dropout_kernels.cu`, for
    the same reason) — per-element threefry costs ~18% of a BERT-Large
    step on TPU (measured); the hash is a handful of fused VPU ops."""
    if deterministic or rate <= 0.0 or rng is None:
        return x
    import numpy as np
    seed = jax.random.randint(rng, (), 0, 2**31 - 1, dtype=jnp.int32)
    n = int(np.prod(x.shape))
    idx = jax.lax.iota(jnp.int32, n)
    h = idx * (-1640531527) ^ seed          # 0x9E3779B9
    h = (h ^ ((h >> 16) & 0xFFFF)) * 0x7FEB352D
    h = (h ^ ((h >> 15) & 0x1FFFF)) * (-2073452917)   # 0x846CA68B
    h = h ^ ((h >> 16) & 0xFFFF)
    thresh = int(min(max(rate, 0.0), 1.0) * 2147483647)
    keep = ((h & 0x7FFFFFFF) >= thresh).reshape(x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


class DeepSpeedTransformerLayer:
    """BERT-style encoder layer with the reference's option surface.

    apply(params, x, attention_mask=None, rng=None, deterministic=None)
    with x [B, S, H]; attention_mask [B, S] (1 = attend) or additive
    [B, 1, 1, S].
    """

    layer_id = 0

    def __init__(self, config, initial_weights=None, initial_biases=None):
        for name in ("attn_dropout_ratio", "hidden_dropout_ratio"):
            rate = getattr(config, name, -1)
            # -1/negative = unset (reference default); >= 1 would make
            # the survivor scale 1/(1-rate) inf/NaN instead of erroring
            if rate >= 1.0:
                raise ValueError(f"{name} must be < 1.0, got {rate}")
        self.config = config
        self.config.layer_id = DeepSpeedTransformerLayer.layer_id
        DeepSpeedTransformerLayer.layer_id += 1
        self.initial_weights = initial_weights
        self.initial_biases = initial_biases

    # -- params ------------------------------------------------------------

    def init(self, rng, x=None):
        cfg = self.config
        h = cfg.hidden_size
        inter = cfg.intermediate_size
        std = cfg.initializer_range if cfg.initializer_range > 0 else 0.02
        out_std = std
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            out_std = std / math.sqrt(2.0 * cfg.num_hidden_layers)
        keys = jax.random.split(rng, 4)
        dtype = jnp.float32

        def dense(key, shape, s):
            return (jax.random.normal(key, shape) * s).astype(dtype)

        params = {
            "attn_qkvw": dense(keys[0], (h, 3 * h), std),
            "attn_qkvb": jnp.zeros((3 * h,), dtype),
            "attn_ow": dense(keys[1], (h, h), out_std),
            "attn_ob": jnp.zeros((h,), dtype),
            "attn_nw": jnp.ones((h,), dtype),
            "attn_nb": jnp.zeros((h,), dtype),
            "inter_w": dense(keys[2], (h, inter), std),
            "inter_b": jnp.zeros((inter,), dtype),
            "output_w": dense(keys[3], (inter, h), out_std),
            "output_b": jnp.zeros((h,), dtype),
            "norm_w": jnp.ones((h,), dtype),
            "norm_b": jnp.zeros((h,), dtype),
        }
        if self.initial_weights is not None:
            qkv = jnp.concatenate(
                [jnp.asarray(w).T for w in self.initial_weights[:3]], axis=1)
            params["attn_qkvw"] = qkv.astype(dtype)
            params["attn_ow"] = jnp.asarray(self.initial_weights[3]).T
            params["attn_nw"] = jnp.asarray(self.initial_weights[4])
            params["inter_w"] = jnp.asarray(self.initial_weights[5]).T
            params["output_w"] = jnp.asarray(self.initial_weights[6]).T
            params["norm_w"] = jnp.asarray(self.initial_weights[7])
        if self.initial_biases is not None:
            qkvb = jnp.concatenate(
                [jnp.asarray(b) for b in self.initial_biases[:3]])
            params["attn_qkvb"] = qkvb.astype(dtype)
            params["attn_ob"] = jnp.asarray(self.initial_biases[3])
            params["attn_nb"] = jnp.asarray(self.initial_biases[4])
            params["inter_b"] = jnp.asarray(self.initial_biases[5])
            params["output_b"] = jnp.asarray(self.initial_biases[6])
            params["norm_b"] = jnp.asarray(self.initial_biases[7])
        return params

    # -- forward -----------------------------------------------------------

    def _attention(self, params, x, attention_mask, rng, deterministic,
                   segment_ids=None):
        cfg = self.config
        b, s, h = x.shape
        heads = cfg.heads
        hd = h // heads
        qkv = x @ params["attn_qkvw"].astype(x.dtype) + \
            params["attn_qkvb"].astype(x.dtype)
        # qkv columns are [Q | K | V] blocks (BERT convention; GPT-NeoX uses
        # per-head interleave instead — see models/gpt_neox.py).
        q, k, v = (t.reshape(b, s, heads, hd)
                   for t in jnp.split(qkv, 3, axis=-1))

        # Per-key masks ([B, S] keep-masks and [B, 1, 1, S] additive — every
        # BERT/SQuAD batch) reduce to a [B, S] additive row that the flash
        # kernel fuses pre-max (reference: attn_softmax taking attn_mask,
        # csrc/transformer/softmax_kernels.cu:18-140). Only full [B, H, S, S]
        # biases fall back to the materialized path.
        additive_mask = None
        kbias = None
        if attention_mask is not None:
            am = jnp.asarray(attention_mask)
            if am.ndim == 2:  # [B or 1, S] keep-mask
                kb = jnp.where(am > 0, 0.0, -1e30).astype(jnp.float32)
                kbias = jnp.broadcast_to(kb, (b, s))
                additive_mask = kbias[:, None, None, :]
            elif am.ndim == 4 and am.shape[1] == 1 and am.shape[2] == 1:
                # [B or 1, 1, 1, S] additive (HF convention); batch-
                # shared masks broadcast up to the kernel's [B, S] form
                kbias = jnp.broadcast_to(
                    am.reshape(am.shape[0], s).astype(jnp.float32),
                    (b, s))
                additive_mask = kbias[:, None, None, :]
            else:
                additive_mask = am.astype(jnp.float32)

        # The fused path covers per-key masks AND in-kernel attention
        # dropout (flash_attention_train mirrors the reference's fused
        # attn_softmax + attn_prob_dropout); only full-rank [B, H, S, S]
        # biases fall back to the materialized path.
        attn_drop_active = (not deterministic and
                            cfg.attn_dropout_ratio > 0 and rng is not None)
        if segment_ids is not None:
            # packed ragged batches (bidirectional): intra-document
            # attention via the segmented flash kernel when the shape
            # and option set allow (no per-key bias, no in-kernel
            # dropout — those kernels carry no segment gate), else the
            # materialized pairwise-mask path below
            if (additive_mask is None and not attn_drop_active and
                    s >= _flash_min_seq() and
                    flash_attention_supported((b, s, heads, hd))):
                from ..autotune import (flash_blocks_for,
                                        flash_bwd_blocks_for)
                from ..pallas.flash_attention import (
                    BLOCK_K, BLOCK_Q, flash_attention_segmented)
                # same tuned geometry + min-seq gating as the dense
                # branch below: the static square default was the
                # measured long-context MFU cliff, and packed encoder
                # batches hit the identical kernels
                shape = (b, s, heads, hd)
                blocks = flash_blocks_for(shape, q.dtype, False)
                bq, bk = blocks if blocks is not None \
                    else (BLOCK_Q, BLOCK_K)
                bwd = flash_bwd_blocks_for(shape, q.dtype, False,
                                           fwd_blocks=blocks)
                ctx = flash_attention_segmented(q, k, v, segment_ids,
                                                False, None, bq, bk, bwd)
                ctx = ctx.reshape(b, s, h)
                return ctx @ params["attn_ow"].astype(x.dtype) + \
                    params["attn_ob"].astype(x.dtype)
            seg_pen = jnp.where(
                segment_ids[:, None, :, None] ==
                segment_ids[:, None, None, :], 0.0, -1e30)  # [B,1,S,S]
            additive_mask = seg_pen if additive_mask is None else \
                additive_mask + seg_pen
        if segment_ids is None and \
                (additive_mask is None or kbias is not None) and \
                s >= _flash_min_seq() and \
                flash_attention_supported((b, s, heads, hd)):
            # measured block geometry for long sequences (and opt-in
            # autotune runs); None keeps the static default — the fused
            # 16k/32k paths previously hard-coded 1024x1024 here
            from ..autotune import flash_blocks_for
            from ..pallas.flash_attention import BLOCK_K, BLOCK_Q
            blocks = flash_blocks_for((b, s, heads, hd), q.dtype, False)
            bq, bk = blocks if blocks is not None else (BLOCK_Q, BLOCK_K)
            if attn_drop_active:
                seed = jax.random.randint(rng, (1,), 0, 2**31 - 1,
                                          dtype=jnp.int32)
                ctx = flash_attention_train(
                    q, k, v, kbias, seed, block_q=bq, block_k=bk,
                    dropout_rate=float(cfg.attn_dropout_ratio))
            elif kbias is None:
                ctx = flash_attention(q, k, v, False, None, bq, bk)
            else:
                ctx = flash_attention_kbias(q, k, v, kbias, False, None,
                                            bq, bk)
        else:
            scale = 1.0 / math.sqrt(hd)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                                preferred_element_type=jnp.float32) * scale
            if additive_mask is not None:
                logits = logits + additive_mask
            probs = jax.nn.softmax(logits, axis=-1)
            probs = _dropout(probs.astype(x.dtype), cfg.attn_dropout_ratio,
                             rng, deterministic)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        ctx = ctx.reshape(b, s, h)
        return ctx @ params["attn_ow"].astype(x.dtype) + \
            params["attn_ob"].astype(x.dtype)

    def _ffn(self, params, x, rng, deterministic):
        inter = x @ params["inter_w"].astype(x.dtype) + \
            params["inter_b"].astype(x.dtype)
        inter = jax.nn.gelu(inter, approximate=False)
        return inter @ params["output_w"].astype(x.dtype) + \
            params["output_b"].astype(x.dtype)

    def apply(self, params, x, attention_mask=None, rng=None,
              deterministic=None, segment_ids=None):
        cfg = self.config
        if deterministic is None:
            deterministic = not cfg.training
        eps = cfg.layer_norm_eps
        rngs = (jax.random.split(rng, 3) if rng is not None
                else (None, None, None))

        def attn_span(x):
            if cfg.pre_layer_norm:
                normed = _layer_norm(x, params["attn_nw"],
                                     params["attn_nb"], eps)
                attn = self._attention(params, normed, attention_mask,
                                       rngs[0], deterministic,
                                       segment_ids=segment_ids)
                return x + _dropout(attn, cfg.hidden_dropout_ratio, rngs[1],
                                    deterministic)
            attn = self._attention(params, x, attention_mask, rngs[0],
                                   deterministic,
                                   segment_ids=segment_ids)
            attn = _dropout(attn, cfg.hidden_dropout_ratio, rngs[1],
                            deterministic)
            return _layer_norm(x + attn, params["attn_nw"],
                               params["attn_nb"], eps)

        def ffn_span(y):
            if cfg.pre_layer_norm:
                normed = _layer_norm(y, params["norm_w"], params["norm_b"],
                                     eps)
                out = self._ffn(params, normed, rngs[2], deterministic)
                return y + _dropout(out, cfg.hidden_dropout_ratio, rngs[2],
                                    deterministic)
            out = self._ffn(params, y, rngs[2], deterministic)
            out = _dropout(out, cfg.hidden_dropout_ratio, rngs[2],
                           deterministic)
            return _layer_norm(y + out, params["norm_w"], params["norm_b"],
                               eps)

        if cfg.attn_dropout_checkpoint or cfg.normalize_invertible:
            attn_span = jax.checkpoint(attn_span)
        if cfg.gelu_checkpoint or cfg.normalize_invertible:
            ffn_span = jax.checkpoint(ffn_span)

        return ffn_span(attn_span(x))

    def forward(self, params, hidden_states, attention_mask=None, **kw):
        return self.apply(params, hidden_states,
                          attention_mask=attention_mask, **kw)

    __call__ = apply
