from .transformer import (DeepSpeedTransformerConfig,
                          DeepSpeedTransformerLayer, TransformerConfig)
