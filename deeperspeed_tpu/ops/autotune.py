"""Kernel-variant autotuner (reference: `csrc/includes/gemm_test.h` — the
transformer layer benchmarks cuBLAS algorithm ids for its GEMMs once at
layer creation and reuses the winner).

XLA already autotunes its own GEMM tilings; the knob that remains OURS is
Pallas kernel launch geometry — e.g. flash-attention block sizes, where
the best choice flips between TPU generations (fat 1024-blocks win on v5e
where per-instance fixed cost dominates; narrower blocks can win where
VMEM is tighter). `Autotuner.pick` times each candidate on the live
device once per (key, device-kind) and caches the winner for the process
lifetime, exactly the reference's measure-once-use-forever contract.

Activation: autotuning runs real device work (a few warm-up fwd+bwd
launches per candidate), so it is opt-in (`DS_TPU_AUTOTUNE=1`) for
ordinary shapes — EXCEPT long sequences: at or beyond
`flash_tune_min_seq()` (default 8192, `DS_FLASH_TUNE_MIN_SEQ`) the
`flash_blocks_for` dispatch always measures, because the one-time probe
is noise next to a single long-context step and the static default
geometry was the measured MFU cliff there (BENCH_r05).
"""

import functools
import os
import time

import jax

_TUNE_ENV = "DS_TPU_AUTOTUNE"


def autotune_enabled():
    return os.environ.get(_TUNE_ENV, "0") not in ("0", "", "false", "False")


def _device_kind():
    try:
        return getattr(jax.devices()[0], "device_kind", "unknown")
    except Exception:
        return "unknown"


class Autotuner:
    """Times callables on the live device, remembers the fastest.

    `pick(key, candidates, run)` → winning candidate. `run(candidate)`
    must execute the kernel variant end-to-end and return something
    blockable (`jax.block_until_ready` is applied). Failures (e.g. a
    block shape Mosaic rejects or VMEM OOM) disqualify the candidate
    rather than raising — mirrors the reference skipping invalid cublas
    algo ids."""

    def __init__(self, warmup=1, iters=3, timer=time.perf_counter):
        self.warmup = warmup
        self.iters = iters
        self.timer = timer
        self._cache = {}

    def cached(self, key):
        return self._cache.get((key, _device_kind()))

    def store(self, key, value):
        """Record a decision without measuring (fallback paths cache
        their default so repeat calls skip the candidate-fitting work)."""
        self._cache[(key, _device_kind())] = value
        return value

    def pick(self, key, candidates, run):
        full_key = (key, _device_kind())
        if full_key in self._cache:
            return self._cache[full_key]
        best, best_t = None, float("inf")
        for cand in candidates:
            try:
                for _ in range(self.warmup):
                    jax.block_until_ready(run(cand))
                t0 = self.timer()
                for _ in range(self.iters):
                    out = run(cand)
                jax.block_until_ready(out)
                dt = self.timer() - t0
            except Exception:
                continue
            if dt < best_t:
                best, best_t = cand, dt
        if best is None:
            raise RuntimeError(
                f"autotune: every candidate failed for key {key!r}")
        self._cache[full_key] = best
        return best


_global_tuner = Autotuner()


def ladder_pick(key, candidates, measure, tuner=None, *,
                measurable=True, default=None):
    """The screen→measure→cache spine shared by every kernel picker in
    this module and by the planner's probe phase
    (`deeperspeed_tpu.planner`). Before this helper the five pickers
    each hand-rolled the same five steps; now they only supply their
    candidate ladder, their probe, and their degrade verdict.

    1. cache hit for (key, device kind) → returned unmeasured
       (measure-once-use-forever);
    2. `measurable` false (caller's verdict: interpret-mode Pallas,
       probe-byte cap, analytic-only planning) or a multi-host run
       (per-host wall-clock picks can disagree → different programs per
       host → deadlock at the first collective) → the deterministic
       `default` is stored without touching the device. When `default`
       is None the candidate ladder's first entry is stored instead;
    3. a ladder that collapses to one survivor → stored unmeasured;
    4. otherwise each candidate is timed via `measure(candidate)` with
       `perf_counter` OUTSIDE traced code and the winner is cached.

    `candidates`, `measurable` and `default` may be zero-arg callables:
    they are resolved only on a cache miss (and `default` only when
    degrading), so expensive screens — the grouped-matmul AOT memory
    screen lowers a composite fwd+bwd program per candidate — and
    cap-exceeded log lines are paid once per (key, device kind), not
    per call."""
    tuner = tuner or _global_tuner
    hit = tuner.cached(key)
    if hit is not None:
        return hit
    if callable(measurable):
        measurable = measurable()
    degraded = not measurable or jax.process_count() > 1
    if degraded:
        if callable(default):
            default = default()
        if default is not None:
            return tuner.store(key, default)
    cands = list(candidates() if callable(candidates) else candidates)
    if not cands:
        raise ValueError(
            f"autotune: no viable candidates for key {key!r}")
    if len(cands) == 1 or degraded:
        return tuner.store(key, cands[0])
    return tuner.pick(key, cands, measure)

# Candidate (block_q, block_k) geometries for the flash kernels, fattest
# first (the v5e-measured winner ordering). Non-square entries exist for
# the compacted causal grid: its trapezoid rows grow with qi, so a fat
# block_q with a narrower block_k keeps per-instance VMEM bounded while
# the schedule (not an in-kernel gate) already skips the dead tiles —
# at 16k/32k the fp32 [BQ, BK] score tile is the VMEM limiter, which
# square 1024² geometry hard-codes at 4 MB.
FLASH_BLOCK_CANDIDATES = ((1024, 1024), (2048, 1024), (1024, 512),
                          (2048, 512), (512, 512), (512, 1024),
                          (1024, 256), (512, 256), (256, 512),
                          (256, 256), (256, 128), (128, 128))


# Above this, standalone benchmark launches aren't representative (and the
# probe arrays would strain device memory) — fall back to the default.
_MAX_TUNE_BYTES = 1 << 30

# Sequences at or above this always take the measured block pick, even
# without DS_TPU_AUTOTUNE=1: at 16k-32k the default square geometry was
# the measured long-context MFU cliff (BENCH_r05 0.21 vs 0.61 at 1k) and
# a one-time per-process probe is noise next to a single long-seq step.
_TUNE_MIN_SEQ_ENV = "DS_FLASH_TUNE_MIN_SEQ"


def flash_tune_min_seq():
    return int(os.environ.get(_TUNE_MIN_SEQ_ENV, "8192"))


# ---------------------------------------------------------------------------
# Compile-time memory screening (tentpole: the (remat policy × batch)
# bench ladder pre-screens rungs with `compiled.memory_analysis()` before
# spending a timed run — an AOT lower+compile over abstract shapes costs
# seconds and zero HBM, an OOM'd rung costs a subprocess, a 30 s zombie-
# buffer grace, and a retry).
# ---------------------------------------------------------------------------

# Per-generation HBM capacities (spec sheet), used when the runtime does
# not report `bytes_limit` (e.g. tunneled backends).
_HBM_BYTES_BY_KIND = {
    "v5 lite": 16 << 30, "v5e": 16 << 30,
    "v5p": 95 << 30,
    "v4": 32 << 30,
    "v6": 32 << 30, "v6e": 32 << 30,
}


def hbm_bytes_limit(device=None):
    """Usable device-memory budget in bytes, or None when unknown (CPU
    backends report no limit — screening is then skipped)."""
    try:
        device = device or jax.devices()[0]
    except Exception:
        return None
    try:
        stats = device.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    kind = (getattr(device, "device_kind", "") or str(device)).lower()
    if getattr(device, "platform", "") != "tpu":
        return None
    for key, val in _HBM_BYTES_BY_KIND.items():
        if key in kind:
            return val
    # unknown TPU kind: no budget rather than a guess — screening must
    # never block a rung it cannot reason about (memory_feasible treats
    # None as "skip the screen")
    return None


def compiled_memory_stats(fn, abstract_args):
    """AOT-compile `fn` over `jax.ShapeDtypeStruct` args (nothing is
    materialized or executed) and return its `memory_analysis()` as a
    dict: argument/output/temp/alias bytes plus a `peak` estimate
    (args + outputs + temps − donated aliases). Returns None when the
    backend provides no analysis."""
    compiled = jax.jit(fn).lower(*abstract_args).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return None

    def field(name):
        v = getattr(ma, name, 0) or 0
        return int(v)

    stats = {
        "argument_bytes": field("argument_size_in_bytes"),
        "output_bytes": field("output_size_in_bytes"),
        "temp_bytes": field("temp_size_in_bytes"),
        "alias_bytes": field("alias_size_in_bytes"),
        "generated_code_bytes": field("generated_code_size_in_bytes"),
    }
    stats["peak"] = max(
        stats["argument_bytes"] + stats["output_bytes"]
        + stats["temp_bytes"] - stats["alias_bytes"], 0)
    return stats


def memory_feasible(fn, abstract_args, budget_bytes=None, safety=0.92,
                    extra_bytes=0):
    """Pre-screen a candidate program: does its compiled peak (plus
    `extra_bytes` of resident state the program does not see, e.g.
    optimizer moments) fit the device budget?

    Returns (fits, stats). Unknown budgets or backends without
    `memory_analysis` return (True, stats_or_None) — screening never
    blocks a rung it cannot reason about; the ladder's subprocess
    isolation still catches real OOMs. `safety` holds back headroom for
    fragmentation and the runtime's own buffers."""
    if budget_bytes is None:
        budget_bytes = hbm_bytes_limit()
    try:
        stats = compiled_memory_stats(fn, abstract_args)
    except Exception as e:  # noqa: BLE001 - screening must not kill rungs
        from ..utils.logging import logger
        logger.info(f"memory screen: AOT compile failed "
                    f"({type(e).__name__}: {e}); skipping screen")
        return True, None
    if stats is None or budget_bytes is None:
        return True, stats
    need = stats["peak"] + int(extra_bytes)
    return need <= budget_bytes * safety, stats


# ---------------------------------------------------------------------------
# grouped expert matmul (ops/pallas/grouped_matmul.py — the sort-based
# MoE dispatch engine's FFN kernel)
# ---------------------------------------------------------------------------

# (block_m, block_n) targets, fattest first. The kernel fits each to the
# actual span/output dims; candidates differing only after fitting are
# deduped before measurement.
GMM_BLOCK_CANDIDATES = ((512, 512), (512, 256), (256, 512), (256, 256),
                        (128, 256), (256, 128), (128, 128))

# Conservative per-instance VMEM bound for the static screen: the fwd
# working set is double-buffered x [bm, K] and w [K, bn] tiles plus the
# output tile; the bwd dw kernel's is the same order with a [K, bn] fp32
# accumulator block in place of the output tile.
_GMM_VMEM_BUDGET = 10 << 20


def gmm_vmem_bytes(block_m, block_n, k_dim, itemsize):
    """Estimated VMEM working set of one grouped-matmul instance
    (fwd/bwd superset): double-buffered input tiles + the fp32
    accumulator/output block (max of the fwd [bm, bn] and dw [K, bn])."""
    return (2 * (block_m * k_dim + k_dim * block_n) * itemsize
            + max(block_m * block_n, k_dim * block_n) * 4)


def _gmm_itemsize(dtype):
    import jax.numpy as jnp
    import numpy as np
    return 2 if dtype == jnp.bfloat16 else np.dtype(dtype).itemsize


def grouped_matmul_blocks(capacity, k_dim, n_dim, dtype, tuner=None):
    """(block_m, block_n) for `grouped_matmul` at the given expert-FFN
    geometry. The SAME block pair serves both FFN matmuls — (k_dim →
    n_dim) and back (n_dim → k_dim) — so candidates are screened
    against the VMEM model at BOTH contraction dims (an over-budget
    geometry is a Mosaic allocation failure, not a slow rung); with
    `DS_TPU_AUTOTUNE=1` the survivors are additionally memory-screened
    via AOT `memory_analysis` and then measured fwd+bwd over the
    composite two-matmul FFN on the live device
    (measure-once-use-forever, like the flash blocks). Without opt-in
    the first screened candidate wins — a deterministic static pick, no
    probe launches at trace time."""
    itemsize = _gmm_itemsize(dtype)
    screened = [c for c in GMM_BLOCK_CANDIDATES
                if max(gmm_vmem_bytes(c[0], c[1], k_dim, itemsize),
                       gmm_vmem_bytes(c[0], c[1], n_dim, itemsize))
                <= _GMM_VMEM_BUDGET]
    if not screened:
        screened = [GMM_BLOCK_CANDIDATES[-1]]
    if not autotune_enabled():
        return screened[0]

    key = ("gmm", int(capacity), int(k_dim), int(n_dim), str(dtype))

    import jax.numpy as jnp
    from .pallas.grouped_matmul import _interpret, grouped_matmul, \
        pick_span

    n_groups = 8

    def build(cand):
        # probe the geometry EXACTLY as the MoE layer deploys it: the
        # composite in->out FFN pair (the second matmul's contraction
        # dim is n_dim — usually the 4x larger one), with pick_span's
        # fitted row block (two candidates can collapse to one pair)
        span, bm = pick_span(capacity, cand[0])
        x = jnp.zeros((n_groups * span, k_dim), dtype)
        w1 = jnp.zeros((n_groups, k_dim, n_dim), dtype)
        w2 = jnp.zeros((n_groups, n_dim, k_dim), dtype)
        sizes = jnp.full((n_groups,), min(int(capacity), span), jnp.int32)

        def run(xv):
            h = grouped_matmul(xv, w1, sizes, span, None, bm, cand[1],
                               backend="pallas")
            out = grouped_matmul(h, w2, sizes, span, None, bm, cand[1],
                                 backend="pallas")
            return jnp.sum(out.astype(jnp.float32))
        return run, x, (bm, cand[1])

    def survivors():
        # AOT memory screen before spending a timed run on a candidate;
        # dedupe candidates that fit to the same deployed geometry.
        # Resolved lazily by ladder_pick: in interpret mode or
        # multi-host this (expensive — one AOT fwd+bwd lowering per
        # candidate) never runs
        out, seen = [], set()
        for cand in screened:
            run, x, fitted = build(cand)
            if fitted in seen:
                continue
            fits, _ = memory_feasible(
                jax.grad(run), (jax.ShapeDtypeStruct(x.shape, x.dtype),))
            if fits:
                seen.add(fitted)
                out.append(cand)
        return out or [screened[0]]

    def measure(cand):
        run, x, _ = build(cand)
        return jax.grad(run)(x)

    return ladder_pick(
        key, screened if len(screened) == 1 else survivors, measure,
        tuner,
        measurable=lambda: not _interpret(), default=screened[0])


# ---------------------------------------------------------------------------
# quantized weight-only matmul (ops/pallas/quant_matmul.py — the serving
# int8 decode/prefill weight path)
# ---------------------------------------------------------------------------

# (block_m, block_k, block_n) targets, fattest first. The weight tile is
# int8 (1 byte/element), so fat k-blocks are cheap on the wire; the fp32
# accumulator block is the VMEM limiter.
QMM_BLOCK_CANDIDATES = ((256, 512, 256), (512, 512, 256), (256, 512, 512),
                        (256, 256, 256), (128, 512, 256), (128, 256, 256),
                        (128, 256, 128))

_QMM_VMEM_BUDGET = 10 << 20


def qmm_vmem_bytes(block_m, block_k, block_n, itemsize):
    """Estimated VMEM working set of one quant-matmul instance:
    double-buffered x (compute dtype) and weight (int8) tiles, the fp32
    accumulator block, the scale row and the output tile."""
    return (2 * block_m * block_k * itemsize        # x tiles
            + 2 * block_k * block_n * 1             # int8 weight tiles
            + block_m * block_n * 4                 # fp32 accumulator
            + block_n * 4                           # scale row
            + block_m * block_n * itemsize)         # output tile


def quant_matmul_blocks(m, k, n, dtype, tuner=None):
    """(block_m, block_k, block_n) for `quant_matmul` at the given call
    geometry: VMEM-model screen always, measured pick on the live device
    under DS_TPU_AUTOTUNE=1 (measure-once-use-forever, like the flash and
    grouped-matmul blocks). Without opt-in the first screened candidate
    wins — a deterministic static pick, no probe launches at trace
    time."""
    itemsize = _gmm_itemsize(dtype)
    screened = [c for c in QMM_BLOCK_CANDIDATES
                if qmm_vmem_bytes(*c, itemsize=itemsize)
                <= _QMM_VMEM_BUDGET]
    if not screened:
        screened = [QMM_BLOCK_CANDIDATES[-1]]
    if not autotune_enabled():
        return screened[0]

    key = ("qmm", int(m), int(k), int(n), str(dtype))

    import jax.numpy as jnp
    from .pallas.quant_matmul import (_fit, _interpret, quant_matmul,
                                      quantize_weight)

    def fitted():
        # dedupe candidates on their FITTED geometry
        out, seen = [], set()
        for c in screened:
            fit = (_fit(c[0], m, 8), _fit(c[1], k, 32),
                   _fit(c[2], n, 128))
            if fit in seen:
                continue
            seen.add(fit)
            out.append(c)
        return out

    probe = {}

    def measure(cand):
        if not probe:  # built once, on the first warmup call only
            probe["x"] = jnp.zeros((m, k), dtype)
            probe["qw"] = quantize_weight(jnp.zeros((k, n), jnp.float32))
        return quant_matmul(probe["x"], probe["qw"], backend="pallas",
                            blocks=cand)

    return ladder_pick(key, fitted, measure, tuner,
                       measurable=lambda: not _interpret(),
                       default=screened[0])


def _fitted_flash_candidates(shape, fit_block, supported):
    """FLASH_BLOCK_CANDIDATES fitted to the call shape and deduped on
    the fitted geometry — several requests can collapse to the same
    block pair and must be measured once. Shared by the fwd and bwd
    pickers (their fit loops were copy-identical)."""
    _, s, _, _ = shape
    out = []
    for c in FLASH_BLOCK_CANDIDATES:
        fit = (fit_block(c[0], s), fit_block(c[1], s))
        if 0 in fit or not supported(shape, *c):
            continue
        if fit not in out:
            out.append(fit)
    if not out:
        raise ValueError(f"no flash block candidates fit shape {shape}")
    return out


def flash_bwd_blocks_for(shape, dtype, causal, fwd_blocks=None,
                         tuner=None):
    """Dispatch-time block geometry for the flash BACKWARD (dkv/dq)
    kernels, or None for "reuse the forward geometry".

    The backward working set per instance is ~2.5× the forward's (q/k/v
    PLUS do tiles, lse/delta rows, fp32 dk/dv/dq accumulators), so the
    measured-best backward blocks at ≥8k sequences are usually narrower
    than the forward winner — PR 1 tuned only the shared geometry, which
    pinned backward to whatever forward preferred. Gating matches
    `flash_blocks_for`: long sequences always measure, DS_TPU_AUTOTUNE=1
    measures everywhere, an explicit DS_TPU_AUTOTUNE=0 is the kill
    switch. The probe times ONLY the vjp application (residuals are
    computed once per candidate outside the timed region via jax.vjp),
    so the pick ranks pure backward cost."""
    env = os.environ.get(_TUNE_ENV)
    if env is not None and env in ("0", "", "false", "False"):
        return None
    b, s, h, d = shape
    if not (autotune_enabled() or s >= flash_tune_min_seq()):
        return None

    from .pallas.flash_attention import (_fit_block, _interpret,
                                         flash_attention,
                                         flash_attention_supported)
    import numpy as np
    import jax.numpy as jnp

    key = ("flash_bwd", tuple(shape), str(dtype), bool(causal))
    candidates = _fitted_flash_candidates(shape, _fit_block,
                                          flash_attention_supported)

    capped = []

    def measurable():
        if _interpret():
            # timing the interpreter ranks emulation cost
            return False
        itemsize = np.dtype(dtype).itemsize if dtype != jnp.bfloat16 \
            else 2
        if b * s * h * d * itemsize * 8 > _MAX_TUNE_BYTES:
            from ..utils.logging import logger
            logger.info(
                f"flash bwd autotune: shape {tuple(shape)} exceeds the "
                f"probe memory cap; reusing forward blocks")
            capped.append(True)
            return False
        return True

    def default():
        # probe-cap degrade inherits the forward geometry; every other
        # degrade (interpret, multi-host) takes the fattest fit
        if capped and fwd_blocks is not None:
            return tuple(fwd_blocks)
        return candidates[0]

    fbq, fbk = fwd_blocks if fwd_blocks is not None else candidates[0]
    bwd_cache = {}

    def measure(cand):
        # vjp ONCE per candidate (fwd geometry held FIXED at fbq/fbk so
        # only the backward differs), memoized so the fwd execution +
        # trace land in the tuner's first warmup call and the timed
        # iterations apply only the bwd closure
        f_bwd = bwd_cache.get(cand)
        if f_bwd is None:
            zeros = bwd_cache.setdefault("zeros",
                                         jnp.zeros(shape, dtype))
            _, f_bwd = jax.vjp(
                lambda q, k, v: flash_attention(q, k, v, causal, None,
                                                fbq, fbk, tuple(cand)),
                zeros, zeros, zeros)
            bwd_cache[cand] = f_bwd
        return f_bwd(bwd_cache["zeros"])

    return ladder_pick(key, candidates, measure, tuner,
                       measurable=measurable, default=default)


# block-sparse attention (group_q, fanout) candidates, fattest first:
# bigger groups amortize per-instance fixed cost when adjacent layout
# rows share columns (windowed/global patterns); bigger fanout fetches
# more scattered K blocks per grid step. Random-ish patterns (BigBird)
# prefer smaller groups — the row union drags dead rows otherwise.
SPARSE_GF_CANDIDATES = ((4, 4), (8, 4), (4, 8), (2, 8), (8, 8), (2, 4),
                        (2, 2), (1, 4))


def sparse_block_params(layout, shape, dtype, causal, sm_scale=None,
                        tuner=None):
    """(group_q, fanout) for `BlockSparseAttention` at a given layout and
    call shape. Static default (4, 4) unless DS_TPU_AUTOTUNE=1, in which
    case the candidates are measured fwd+bwd on the live device with the
    ACTUAL layout (pattern structure decides the winner: the row-union
    LUT tightness differs wildly between windowed and random patterns).
    Cached per (layout geometry, density, shape, device kind)."""
    default = SPARSE_GF_CANDIDATES[0]
    if not autotune_enabled():
        return default
    from .pallas.block_sparse_attention import BlockSparseAttention
    from .pallas.flash_attention import _interpret
    import numpy as np
    import jax.numpy as jnp

    lay = np.asarray(layout)
    key = ("sparse_gf", lay.shape, round(float((lay != 0).mean()), 3),
           tuple(shape), str(dtype), bool(causal))

    probe = {}

    def measure(cand):
        zeros = probe.setdefault("z", jnp.zeros(shape, dtype))
        attn = BlockSparseAttention(lay, block=128, causal=causal,
                                    sm_scale=sm_scale, group=cand[0],
                                    fanout=cand[1])
        return jax.grad(lambda q: jnp.sum(
            attn(q, zeros, zeros).astype(jnp.float32)))(zeros)

    return ladder_pick(key, SPARSE_GF_CANDIDATES, measure, tuner,
                       measurable=lambda: not _interpret(),
                       default=default)


def flash_blocks_for(shape, dtype, causal, tuner=None):
    """Dispatch-time flash block geometry, or None for the built-in
    default. Long sequences (≥ `flash_tune_min_seq()`, env-tunable) and
    explicit `DS_TPU_AUTOTUNE=1` runs get `tuned_flash_blocks`'s
    measured pick; everything else keeps the static default so short-seq
    call sites pay zero probe launches. Multi-host and oversized shapes
    degrade to the deterministic fattest candidate inside the tuner.

    `DS_TPU_AUTOTUNE=0` set EXPLICITLY is a kill switch: no measurement
    anywhere, long sequences included (determinism / trace-latency /
    probe-crash escape hatch). Unset means auto (long-seq only)."""
    env = os.environ.get(_TUNE_ENV)
    if env is not None and env in ("0", "", "false", "False"):
        return None
    b, s, h, d = shape
    if autotune_enabled() or s >= flash_tune_min_seq():
        return tuned_flash_blocks(shape, dtype, causal, tuner=tuner)
    return None


def tuned_flash_blocks(shape, dtype, causal, tuner=None):
    """Pick (block_q, block_k) for `flash_attention` by measurement.

    shape: the [B, S, H, D] call shape as seen at the call site — under
    GSPMD tracing that is the GLOBAL shape, so results are a geometry
    heuristic, not a per-shard measurement. Cached per (shape, dtype,
    causal, device kind); the first miss pays a few kernel launches.
    NOTE: that measurement runs EAGERLY during the first jit trace of any
    step calling this — budget the one-time latency accordingly.
    Oversized shapes and multi-host runs skip measurement and cache the
    fattest default.

    The probe runs forward AND backward: the picked geometry feeds the
    bwd dkv/dq kernels too, whose VMEM working set is larger — a
    candidate that only fails (or only crawls) in backward must lose
    here, not at the first jax.grad step of training."""
    from .pallas.flash_attention import (_fit_block, flash_attention,
                                         flash_attention_supported)
    import numpy as np
    import jax.numpy as jnp

    from .pallas.flash_attention import _interpret
    b, s, h, d = shape
    key = ("flash", tuple(shape), str(dtype), bool(causal))

    def candidates():
        return _fitted_flash_candidates(shape, _fit_block,
                                        flash_attention_supported)

    def measurable():
        # Interpret mode (CPU): measuring would rank Pallas-interpreter
        # emulation cost — and a 16k probe takes MINUTES per candidate
        # there. (Multi-host degrade lives in ladder_pick.)
        if _interpret():
            return False
        # x8: the fwd+bwd probe's live set is q/k/v/out + saved
        # residuals + the cotangent and dq/dk/dv inside _bwd — about
        # twice the old forward-only probe's four arrays
        itemsize = np.dtype(dtype).itemsize if dtype != jnp.bfloat16 \
            else 2
        if b * s * h * d * itemsize * 8 > _MAX_TUNE_BYTES:
            # not silent: the shapes most likely to hit this cap (big
            # GSPMD global batches at 16k+) are exactly what tuning
            # targets
            from ..utils.logging import logger
            logger.info(
                f"flash autotune: shape {tuple(shape)} exceeds the "
                f"probe memory cap; using the fattest fitted blocks")
            return False
        return True

    probe = {}

    def run(cand):
        zeros = probe.setdefault("z", jnp.zeros(shape, dtype))
        return jax.grad(lambda q: jnp.sum(
            flash_attention(q, zeros, zeros, causal, None, *cand)
            .astype(jnp.float32)))(zeros)

    return ladder_pick(key, candidates, run, tuner,
                       measurable=measurable)
