"""Grouped (ragged) expert matmul as a Pallas TPU kernel.

The sort-based MoE dispatch engine (`moe/layer.py`, `dispatch="sort"`)
permutes routed tokens into per-expert contiguous spans and needs
``y[r] = x[r] @ w[expert_of(r)]`` over that buffer. The GShard einsum
formulation spends MXU flops multiplying the [T, E, C] one-hot dispatch
tensor — at top-2/cf=1.25 most of them against zeros; this kernel runs
ONLY the real expert matmuls, one `pallas_call` for all experts.

Contract (shared by kernel and XLA fallback):

- ``x`` [R, K]: rows grouped into G contiguous spans of ``span`` rows
  each (R = G·span). Spans are the caller's capacity bound rounded up to
  the row-block size.
- ``w`` [W, K, N]: stacked weights. Span s multiplies ``w[lut[s]]`` —
  ``lut`` is a STATIC non-decreasing map (spans of one weight must be
  contiguous; identity when G == W). Expert parallelism uses it to point
  the ep·g spans received from every source rank at this rank's local
  expert weights.
- ``group_sizes`` [G] int32 (traced): valid rows per span — the RAGGED
  part (actual routed counts, including empty experts). Rows at or past
  the size produce exact-zero output (masked tail tiles), contribute
  nothing to ``dw``, and receive zero ``dx``.

Mechanics: the grid is (N/bn, R/bm) with the row dimension innermost, so
consecutive instances stream one weight's row tiles while its [K, bn]
tile stays VMEM-resident. A scalar-prefetched LUT
(`pltpu.PrefetchScalarGridSpec`) resolves row tile → weight row in the
BlockSpec index map; prefetched group sizes drive the in-kernel tail
masks, and tiles entirely past their span's size skip the MXU work
(`pl.when`). Backward is a `custom_vjp`: dx reuses the forward kernel
against w^T; dw accumulates x^T·dy tiles into a revisited fp32 output
block (zeroed at each weight's first visit — the flash dkv pattern).

On non-TPU backends the kernel runs in interpreter mode (slow,
test-only); `grouped_matmul` defaults to the XLA fallback there, a
batched segment einsum with the same masking semantics.
"""

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams
from .flash_attention import _interpret

DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256

_DIMSEM = CompilerParams(dimension_semantics=("parallel", "arbitrary"))


class GmmSpec(NamedTuple):
    """Static launch geometry (hashable — rides custom_vjp
    nondiff_argnums)."""
    span: int       # rows per group span (multiple of block_m)
    lut: tuple      # span index -> weight row (non-decreasing)
    block_m: int
    block_n: int
    interpret: bool


def _fit_rows(block, span):
    """Largest row-block ≤ `block` dividing `span` (8-aligned when
    possible — the fp32 sublane tile)."""
    if span <= block:
        return span
    for cand in range(block - block % 8, 7, -8):
        if span % cand == 0:
            return cand
    return span if span <= 2 * block else 8


def _fit_cols(block, n):
    """Largest 128-multiple ≤ `block` dividing n; n itself when no
    128-aligned divisor exists (interpret-mode shapes)."""
    for cand in range(min(block, n), 127, -128):
        if cand % 128 == 0 and n % cand == 0:
            return cand
    return n


def pick_span(capacity, block_m=None):
    """(span, block_m) for a grouped-matmul buffer: span = capacity
    rounded up to the row-block, preferring fat blocks but never padding
    a span by more than ~12.5% (padding is wasted HBM in the dense MoE
    path and wasted ICI in the expert-parallel exchange). Small
    capacities degrade to a single 8-aligned tile per span. Shared by
    the MoE layer and the autotuner so the measured geometry is exactly
    the deployed one."""
    cap = max(1, int(capacity))
    target = int(block_m) if block_m else DEFAULT_BLOCK_M
    for cand in (target, target // 2, target // 4):
        if cand >= 8:
            span = -(-cap // cand) * cand
            if span - cap <= max(cap // 8, 7):
                return span, cand
    span = -(-cap // 8) * 8
    return span, span


def grouped_matmul_supported(k, n, span):
    """Mosaic constraints for the real-TPU kernel: 128-aligned
    contraction/output minor dims, 8-aligned spans. Interpret mode
    (CPU tests) has no tiling rules."""
    if _interpret():
        return True
    return k % 128 == 0 and n % 128 == 0 and span % 8 == 0


# ---------------------------------------------------------------------------
# forward kernel (also computes dx against w^T in backward)
# ---------------------------------------------------------------------------

def _fwd_kernel(lut_ref, sizes_ref, x_ref, w_ref, o_ref, *, tpg, block_m,
                block_n):
    i = pl.program_id(1)
    g = i // tpg
    row0 = (i % tpg) * block_m
    size = sizes_ref[g]

    @pl.when(row0 < size)
    def _run():
        acc = jax.lax.dot_general(
            x_ref[...], w_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_m, block_n), 0) + row0
        o_ref[...] = jnp.where(rows < size, acc, 0.0).astype(o_ref.dtype)

    @pl.when(row0 >= size)
    def _dead():
        o_ref[...] = jnp.zeros_like(o_ref)


def _gmm_pallas(x, w, sizes, spec):
    R, K = x.shape
    _, _, N = w.shape
    tpg = spec.span // spec.block_m
    grid = (N // spec.block_n, R // spec.block_m)
    kernel = functools.partial(_fwd_kernel, tpg=tpg,
                               block_m=spec.block_m, block_n=spec.block_n)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((R, N), x.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((spec.block_m, K),
                             lambda j, i, lut, sz: (i, 0)),
                pl.BlockSpec((1, K, spec.block_n),
                             lambda j, i, lut, sz: (lut[i // tpg], 0, j)),
            ],
            out_specs=pl.BlockSpec((spec.block_m, spec.block_n),
                                   lambda j, i, lut, sz: (i, j)),
        ),
        compiler_params=_DIMSEM,
        interpret=spec.interpret,
    )
    return call(jnp.asarray(spec.lut, jnp.int32), sizes, x, w)


# ---------------------------------------------------------------------------
# dw kernel: accumulate x^T @ dy per weight over its spans' row tiles
# ---------------------------------------------------------------------------

def _dw_kernel(lut_ref, sizes_ref, x_ref, dy_ref, dw_ref, *, tpg, block_m,
               block_n):
    i = pl.program_id(1)
    g = i // tpg
    row0 = (i % tpg) * block_m
    size = sizes_ref[g]
    wsel = lut_ref[g]
    prev = lut_ref[jnp.maximum(g - 1, 0)]
    # first row tile of this weight in the current j sweep: row tiles run
    # innermost, so the output block is revisited for every tile of the
    # weight and must be zeroed exactly once per sweep
    first = jnp.logical_or(i == 0,
                           jnp.logical_and(jnp.logical_and(row0 == 0,
                                                           i % tpg == 0),
                                           wsel != prev))

    @pl.when(first)
    def _zero():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    @pl.when(row0 < size)
    def _acc():
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_m, block_n), 0) + row0
        dyb = jnp.where(rows < size, dy_ref[...], 0).astype(dy_ref.dtype)
        dw_ref[...] += jax.lax.dot_general(
            x_ref[...], dyb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]


def _dw_pallas(x, dy, sizes, spec, n_weights):
    R, K = x.shape
    _, N = dy.shape
    tpg = spec.span // spec.block_m
    grid = (N // spec.block_n, R // spec.block_m)
    kernel = functools.partial(_dw_kernel, tpg=tpg,
                               block_m=spec.block_m, block_n=spec.block_n)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_weights, K, N), jnp.float32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((spec.block_m, K),
                             lambda j, i, lut, sz: (i, 0)),
                pl.BlockSpec((spec.block_m, spec.block_n),
                             lambda j, i, lut, sz: (i, j)),
            ],
            out_specs=pl.BlockSpec((1, K, spec.block_n),
                                   lambda j, i, lut, sz:
                                   (lut[i // tpg], 0, j)),
        ),
        compiler_params=_DIMSEM,
        interpret=spec.interpret,
    )
    return call(jnp.asarray(spec.lut, jnp.int32), sizes, x, dy)


# ---------------------------------------------------------------------------
# custom_vjp assembly
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm(x, w, sizes, spec):
    return _gmm_pallas(x, w, sizes, spec)


def _gmm_vjp_fwd(x, w, sizes, spec):
    return _gmm_pallas(x, w, sizes, spec), (x, w, sizes)


def _gmm_vjp_bwd(spec, res, dy):
    x, w, sizes = res
    # dx = dy @ w^T: the forward kernel against transposed weights; its
    # row mask also zeroes dx for tail rows
    dx_spec = spec._replace(block_n=_fit_cols(spec.block_n, w.shape[1]))
    dx = _gmm_pallas(dy, jnp.swapaxes(w, 1, 2), sizes, dx_spec)
    dw = _dw_pallas(x, dy, sizes, spec, w.shape[0]).astype(w.dtype)
    return dx, dw, np.zeros(sizes.shape, jax.dtypes.float0)


_gmm.defvjp(_gmm_vjp_fwd, _gmm_vjp_bwd)


# ---------------------------------------------------------------------------
# XLA fallback: batched segment einsum with identical masking semantics
# ---------------------------------------------------------------------------

def grouped_matmul_xla(x, w, group_sizes, span, lut=None):
    """Pure-XLA reference/fallback. Spans sharing a weight (a uniform
    repeat LUT — the expert-parallel layout) collapse into one batched
    einsum over the weight dim; arbitrary LUTs gather per-span weights.
    Differentiable natively (the segment masks make dw/dx match the
    kernel's tail-row semantics)."""
    R, K = x.shape
    n_w, _, N = w.shape
    G = R // span
    lut_arr = (np.arange(n_w, dtype=np.int32) if lut is None
               else np.asarray(lut, np.int32))
    valid = (jnp.arange(span)[None, :]
             < group_sizes[:, None])[..., None]          # [G, span, 1]
    reps = G // n_w
    if n_w * reps == G and np.array_equal(
            lut_arr, np.repeat(np.arange(n_w), reps)):
        x4 = x.reshape(n_w, reps * span, K)
        y = jnp.einsum("gsk,gkn->gsn", x4, w,
                       preferred_element_type=jnp.float32)
        y = y.reshape(G, span, N)
    else:
        x3 = x.reshape(G, span, K)
        y = jnp.einsum("gsk,gkn->gsn", x3, w[lut_arr],
                       preferred_element_type=jnp.float32)
    return jnp.where(valid, y, 0.0).astype(x.dtype).reshape(R, N)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def grouped_matmul(x, w, group_sizes, span, lut=None, block_m=None,
                   block_n=None, backend=None):
    """y[r] = x[r] @ w[lut[r // span]] with ragged tail masking.

    backend: None = auto (Pallas kernel on TPU when the shape satisfies
    `grouped_matmul_supported`, XLA fallback otherwise — CPU test runs
    keep XLA speed unless a test opts into the interpreter);
    "pallas" forces the kernel (interpret-mode off-TPU); "xla" forces
    the fallback.
    """
    R, K = x.shape
    n_w, kw, N = w.shape
    if kw != K:
        raise ValueError(f"w contraction dim {kw} != x feature dim {K}")
    if span < 1 or R % span:
        raise ValueError(f"span={span} must divide the {R} buffer rows")
    G = R // span
    lut_t = tuple(range(n_w)) if lut is None else tuple(int(v) for v in lut)
    if len(lut_t) != G:
        raise ValueError(f"lut has {len(lut_t)} entries for {G} spans")
    if any(b > a for a, b in zip(lut_t[1:], lut_t)) or \
            set(lut_t) != set(range(n_w)):
        # every weight must be covered: the dw kernel only writes the
        # output blocks of visited weights — a gap LUT would return
        # uninitialized memory as the skipped weight's gradient
        raise ValueError("lut must be non-decreasing and cover every "
                         "weight row 0..n_w-1 (spans of one weight "
                         "contiguous, no gaps)")
    if group_sizes.shape != (G,):
        raise ValueError(f"group_sizes shape {group_sizes.shape} != ({G},)")

    if backend is None:
        on_tpu = not _interpret()
        backend = ("pallas" if on_tpu and grouped_matmul_supported(K, N, span)
                   else "xla")
    if backend == "xla":
        return grouped_matmul_xla(x, w, group_sizes, span, lut_t)
    if backend != "pallas":
        raise ValueError(f"unknown grouped_matmul backend {backend!r}")

    spec = GmmSpec(
        span=span, lut=lut_t,
        block_m=_fit_rows(block_m or DEFAULT_BLOCK_M, span),
        block_n=_fit_cols(block_n or DEFAULT_BLOCK_N, N),
        interpret=_interpret())
    return _gmm(x, w, group_sizes.astype(jnp.int32), spec)
