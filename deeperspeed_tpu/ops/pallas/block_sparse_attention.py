"""Block-sparse flash attention (splash-attention-style) Pallas kernels.

TPU-native replacement for the reference's Triton block-sparse stack
(`deepspeed/ops/sparse_attention/trsrc/{matmul.tr,softmax_*.tr}` driven by
`matmul.py`/`softmax.py`): instead of materializing block-sparse score
matrices through separate SDD-matmul → sparse-softmax → DSD-matmul passes,
one fused kernel visits ONLY the active column blocks of each query-row
block, carried by a scalar-prefetched LUT, with online softmax — compute
and HBM traffic both scale with the number of active blocks.

Layout comes from `SparsityConfig.make_layout(seq)` →
[num_heads, nQ, nK] 0/1 (see `..sparse_attention.sparsity_config`).
`causal=True` applies an element-level triangular mask inside diagonal
blocks (unidirectional patterns).

**Rectangular grouping + K-fanout** (round-4 redesign; the previous
square GROUP×GROUP coarse tiling computed every 128×128 sub-block of a
coarse tile — random patterns share almost no coarse columns, so MXU
work barely dropped with density and per-instance fixed cost dominated):

- the Q side groups `group_q` adjacent 128-row blocks into one tile
  (adjacent rows of windowed/global patterns share most columns, so the
  row-union LUT stays tight);
- the K side stays FINE: the LUT lists individual active 128-column
  blocks, each fetched through its own input ref — `fanout` refs per
  instance, so one grid step processes `fanout` scattered K/V blocks
  back-to-back (fat [group_q·128, fanout·128] score matmuls, no dead
  coarse sub-blocks on the K axis);
- per-entry activity bits (bit r = fine row r of the group attends this
  column block) mask rows dragged in by the union.

Instance count drops ~group_q·fanout× vs one-block-per-instance and MXU
work tracks the ACTIVE block count — the speedup scales with density.
"""

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams

from .flash_attention import LANES, NEG_INF, _interpret

DEFAULT_BLOCK = 128
DEFAULT_GROUP = 4
DEFAULT_FANOUT = 4


def build_lut(layout):
    """[H, nQ, nK] 0/1 layout → (lut [H, nQ, maxA] int32, sentinel).

    lut[h, qi, :] lists the active column blocks for query-row block qi
    (padded with `sentinel` = nK). For the backward dk/dv kernel call with
    layout.transpose(0, 2, 1)."""
    layout = np.asarray(layout)
    h, n_q, n_k = layout.shape
    counts = layout.sum(axis=2)
    max_active = max(1, int(counts.max()))
    lut = np.full((h, n_q, max_active), n_k, np.int32)
    for hi in range(h):
        for qi in range(n_q):
            cols = np.nonzero(layout[hi, qi])[0]
            lut[hi, qi, :len(cols)] = cols
    return lut, n_k


def build_row_union_lut(layout, group_q, fanout):
    """Row-union fine-column LUT: group `group_q` adjacent 128-row
    blocks; list each group's UNION of active fine column blocks, padded
    to a multiple of `fanout` with sentinel (= nK).

    Returns (lut [H, nGq, maxU] int32, bits [H, nGq, maxU] int32,
    sentinel): bit r of bits[h, g, a] says fine row g*group_q + r is
    active for fine column lut[h, g, a]."""
    layout = np.asarray(layout)
    h, n_q, n_k = layout.shape
    if n_q % group_q:
        raise ValueError(f"{n_q} row blocks not divisible by {group_q}")
    n_gq = n_q // group_q
    grouped = layout.reshape(h, n_gq, group_q, n_k)
    union = grouped.any(axis=2)               # [H, nGq, nK]
    max_u = max(1, int(union.sum(axis=2).max()))
    max_u = -(-max_u // fanout) * fanout      # pad to fanout multiple
    lut = np.full((h, n_gq, max_u), n_k, np.int32)
    bits = np.zeros((h, n_gq, max_u), np.int32)
    rowshift = np.arange(group_q)
    for hi in range(h):
        for g in range(n_gq):
            cols = np.nonzero(union[hi, g])[0]
            lut[hi, g, :len(cols)] = cols
            for a, col in enumerate(cols):
                rows = grouped[hi, g, :, col]           # [group_q]
                bits[hi, g, a] = int((rows.astype(np.int64)
                                      << rowshift).sum())
    return lut, bits, n_k


def _row_bits_mask(s, bits, base_block):
    """Mask score ROWS whose fine row-block is inactive for this fine
    column block: bit r of `bits` covers rows [r·128, (r+1)·128)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // base_block
    return jnp.where(((bits >> rows) & 1) == 1, s, NEG_INF)


def _col_bits_mask(s, bits, base_block):
    """Transposed variant (dk/dv): bit c covers score COLUMNS
    [c·128, (c+1)·128)."""
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) // base_block
    return jnp.where(((bits >> cols) & 1) == 1, s, NEG_INF)


def _fine_causal(s, q_fine0, k_fine, block):
    """Causal mask for a [R·128, 128] strip: rows are fine blocks
    starting at q_fine0, columns the single fine block k_fine."""
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + \
        q_fine0 * block
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + \
        k_fine * block
    return jnp.where(rows >= cols, s, NEG_INF)


def _lut_at(lut_ref, h, gi, ai, *, n_g, max_u):
    return lut_ref[h * n_g * max_u + gi * max_u + ai]


def _entry_map(lut_ref, bh, gi, ai, j, *, num_heads, max_u, n_g, fanout,
               sentinel):
    """Block index for LUT entry ai*fanout + j; padded slots fetch 0."""
    ki = _lut_at(lut_ref, bh % num_heads, gi, ai * fanout + j,
                 n_g=n_g, max_u=max_u)
    return jax.lax.select(ki < sentinel, ki, 0)


def _sparse_fwd_kernel(lut_ref, bits_ref, q_ref, *rest, sm_scale, causal,
                       block, group_q, fanout, num_heads, max_u,
                       sentinel):
    kv_refs = rest[:2 * fanout]
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest[2 * fanout:]
    bh = pl.program_id(0)
    gi = pl.program_id(1)
    ai = pl.program_id(2)
    h = bh % num_heads
    n_g = pl.num_programs(1)

    @pl.when(ai == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]                                        # [Gq·128, D]
    strips = []
    any_active = False
    for j in range(fanout):
        ki = _lut_at(lut_ref, h, gi, ai * fanout + j, n_g=n_g,
                     max_u=max_u)
        active = ki < sentinel
        any_active = jnp.logical_or(any_active, active) \
            if j else active
        k = kv_refs[2 * j][0]                           # [128, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * \
            sm_scale                                    # [Gq·128, 128]
        bits = bits_ref[h * n_g * max_u + gi * max_u + ai * fanout + j]
        s = _row_bits_mask(s, bits, block)
        if causal:
            s = _fine_causal(s, gi * group_q, ki, block)
        # padded entries (ki == sentinel → block 0 fetched) are dead
        s = jnp.where(active, s, NEG_INF)
        strips.append(s)

    @pl.when(any_active)
    def _compute():
        s = jnp.concatenate(strips, axis=1)             # [Gq·128, F·128]
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # rows with every entry masked: exp(NEG_INF - NEG_INF) = 1 —
        # zero them so l==0 flags the dead row
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        v = jnp.concatenate([kv_refs[2 * j + 1][0]
                             for j in range(fanout)], axis=0)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ai == pl.num_programs(2) - 1)
    def _finalize():
        # Rows with NO active blocks (dragged in by the row union) have
        # l == 0: emit 0 and poison their lse to +|NEG_INF| so backward
        # p = exp(s - lse) is exactly 0.
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = jnp.where(l == 0.0, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, -NEG_INF,
                        m_scr[:, :1] + jnp.log(l_safe))
        lse_ref[0] = lse.reshape(1, -1)


def sparse_attention_fwd(q, k, v, lut, bits, sentinel, causal, sm_scale,
                         block, group_q, fanout):
    b, s, h, d = q.shape

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    n_gq = s // (block * group_q)
    max_u = lut.shape[-1]
    lut_flat = jnp.asarray(lut.reshape(-1), jnp.int32)
    bits_flat = jnp.asarray(bits.reshape(-1), jnp.int32)

    kernel = functools.partial(
        _sparse_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block=block, group_q=group_q, fanout=fanout, num_heads=h,
        max_u=max_u, sentinel=sentinel)

    emap = functools.partial(_entry_map, num_heads=h, max_u=max_u,
                             n_g=n_gq, fanout=fanout, sentinel=sentinel)

    in_specs = [pl.BlockSpec((1, block * group_q, d),
                             lambda bh, gi, ai, lref, bref: (bh, gi, 0))]
    inputs = [qb]
    for j in range(fanout):
        in_specs.append(pl.BlockSpec(
            (1, block, d),
            lambda bh, gi, ai, lref, bref, j=j:
            (bh, emap(lref, bh, gi, ai, j), 0)))
        inputs.append(kb)
        in_specs.append(pl.BlockSpec(
            (1, block, d),
            lambda bh, gi, ai, lref, bref, j=j:
            (bh, emap(lref, bh, gi, ai, j), 0)))
        inputs.append(vb)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, n_gq, max_u // fanout),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block * group_q, d),
                         lambda bh, gi, ai, lref, bref: (bh, gi, 0)),
            pl.BlockSpec((1, 1, block * group_q),
                         lambda bh, gi, ai, lref, bref: (bh, 0, gi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block * group_q, LANES), jnp.float32),
            pltpu.VMEM((block * group_q, LANES), jnp.float32),
            pltpu.VMEM((block * group_q, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lut_flat, bits_flat, *inputs)

    out4 = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out4, (qb, kb, vb, out, lse.reshape(b * h, s))


def _sparse_dkv_kernel(lut_ref, bits_ref, k_ref, v_ref, *rest, sm_scale,
                       causal, block, group_k, fanout, num_heads, max_u,
                       sentinel):
    """Grid over GROUPED column blocks (k/v/dk/dv tiles [Gk·128, D]);
    each instance processes `fanout` active fine ROW blocks from the
    transposed-layout LUT, fetching q/do/lse/delta per entry."""
    per = rest[:4 * fanout]
    dk_ref, dv_ref, dk_scr, dv_scr = rest[4 * fanout:]
    bh = pl.program_id(0)
    gi = pl.program_id(1)
    ai = pl.program_id(2)
    h = bh % num_heads
    n_g = pl.num_programs(1)

    @pl.when(ai == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k = k_ref[0]                                        # [Gk·128, D]
    v = v_ref[0]
    for j in range(fanout):
        qi = _lut_at(lut_ref, h, gi, ai * fanout + j, n_g=n_g,
                     max_u=max_u)
        active = qi < sentinel
        q = per[4 * j][0]                               # [128, D]
        do = per[4 * j + 1][0]
        lse = per[4 * j + 2][0].reshape(-1, 1)          # [128, 1]
        delta = per[4 * j + 3][0].reshape(-1, 1)

        @pl.when(active)
        def _one(q=q, do=do, lse=lse, delta=delta, qi=qi, j=j):
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * sm_scale                              # [128, Gk·128]
            bits = bits_ref[h * n_g * max_u + gi * max_u
                            + ai * fanout + j]
            s = _col_bits_mask(s, bits, block)
            if causal:
                # rows: fine block qi; cols: fine blocks gi·Gk ...
                rows = jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0) + qi * block
                cols = jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 1) + gi * (s.shape[1])
                s = jnp.where(rows >= cols, s, NEG_INF)
            p = jnp.exp(s - lse)                        # [128, Gk·128]
            dv_scr[:] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            dk_scr[:] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(ai == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sparse_dq_kernel(lut_ref, bits_ref, q_ref, do_ref, lse_ref,
                      delta_ref, *rest, sm_scale, causal, block, group_q,
                      fanout, num_heads, max_u, sentinel):
    """Row-grouped like the forward kernel; k/v fetched per entry."""
    kv_refs = rest[:2 * fanout]
    dq_ref, dq_scr = rest[2 * fanout:]
    bh = pl.program_id(0)
    gi = pl.program_id(1)
    ai = pl.program_id(2)
    h = bh % num_heads
    n_g = pl.num_programs(1)

    @pl.when(ai == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q = q_ref[0]                                        # [Gq·128, D]
    do = do_ref[0]
    lse = lse_ref[0].reshape(-1, 1)
    delta = delta_ref[0].reshape(-1, 1)
    for j in range(fanout):
        ki = _lut_at(lut_ref, h, gi, ai * fanout + j, n_g=n_g,
                     max_u=max_u)
        active = ki < sentinel
        k = kv_refs[2 * j][0]                           # [128, D]
        v = kv_refs[2 * j + 1][0]

        @pl.when(active)
        def _one(k=k, v=v, ki=ki, j=j):
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * sm_scale                              # [Gq·128, 128]
            bits = bits_ref[h * n_g * max_u + gi * max_u
                            + ai * fanout + j]
            s = _row_bits_mask(s, bits, block)
            if causal:
                s = _fine_causal(s, gi * group_q, ki, block)
            p = jnp.exp(s - lse)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            dq_scr[:] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(ai == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def sparse_attention_bwd(res, g, lut, bits, lut_t, bits_t, sentinel,
                         causal, sm_scale, block, group_q, fanout):
    qb, kb, vb, out, lse = res
    bh, s, d = qb.shape
    bdim = g.shape[0]
    h = bh // bdim
    do = g.transpose(0, 2, 1, 3).reshape(bh, s, d)
    lse = lse.reshape(bh, 1, s)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, s)

    n_g = s // (block * group_q)
    max_u, max_ut = lut.shape[-1], lut_t.shape[-1]
    lut_flat = jnp.asarray(lut.reshape(-1), jnp.int32)
    bits_flat = jnp.asarray(bits.reshape(-1), jnp.int32)
    lut_t_flat = jnp.asarray(lut_t.reshape(-1), jnp.int32)
    bits_t_flat = jnp.asarray(bits_t.reshape(-1), jnp.int32)

    # dk/dv: grid over grouped COLUMN blocks; transposed-layout LUT
    # lists active fine row blocks.
    remap = functools.partial(_entry_map, num_heads=h, max_u=max_ut,
                              n_g=n_g, fanout=fanout, sentinel=sentinel)
    dkv_kernel = functools.partial(
        _sparse_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block=block, group_k=group_q, fanout=fanout, num_heads=h,
        max_u=max_ut, sentinel=sentinel)
    dkv_specs = [
        pl.BlockSpec((1, block * group_q, d),
                     lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
        pl.BlockSpec((1, block * group_q, d),
                     lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
    ]
    dkv_inputs = [kb, vb]
    for j in range(fanout):
        for arr, width in ((qb, block), (do, block)):
            dkv_specs.append(pl.BlockSpec(
                (1, width, d),
                lambda b_, gi, ai, lref, bref, j=j:
                (b_, remap(lref, b_, gi, ai, j), 0)))
            dkv_inputs.append(arr)
        for arr in (lse, delta):
            dkv_specs.append(pl.BlockSpec(
                (1, 1, block),
                lambda b_, gi, ai, lref, bref, j=j:
                (b_, 0, remap(lref, b_, gi, ai, j))))
            dkv_inputs.append(arr)
    dkv_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, n_g, max_ut // fanout),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block * group_q, d),
                         lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
            pl.BlockSpec((1, block * group_q, d),
                         lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block * group_q, d), jnp.float32),
            pltpu.VMEM((block * group_q, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel, grid_spec=dkv_grid,
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), kb.dtype),
            jax.ShapeDtypeStruct((bh, s, d), vb.dtype),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lut_t_flat, bits_t_flat, *dkv_inputs)

    # dq: row-grouped; k/v per entry.
    emap = functools.partial(_entry_map, num_heads=h, max_u=max_u,
                             n_g=n_g, fanout=fanout, sentinel=sentinel)
    dq_kernel = functools.partial(
        _sparse_dq_kernel, sm_scale=sm_scale, causal=causal, block=block,
        group_q=group_q, fanout=fanout, num_heads=h, max_u=max_u,
        sentinel=sentinel)
    dq_specs = [
        pl.BlockSpec((1, block * group_q, d),
                     lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
        pl.BlockSpec((1, block * group_q, d),
                     lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
        pl.BlockSpec((1, 1, block * group_q),
                     lambda b_, gi, ai, lref, bref: (b_, 0, gi)),
        pl.BlockSpec((1, 1, block * group_q),
                     lambda b_, gi, ai, lref, bref: (b_, 0, gi)),
    ]
    dq_inputs = [qb, do, lse, delta]
    for j in range(fanout):
        for arr in (kb, vb):
            dq_specs.append(pl.BlockSpec(
                (1, block, d),
                lambda b_, gi, ai, lref, bref, j=j:
                (b_, emap(lref, b_, gi, ai, j), 0)))
            dq_inputs.append(arr)
    dq_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, n_g, max_u // fanout),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec(
            (1, block * group_q, d),
            lambda b_, gi, ai, lref, bref: (b_, gi, 0)),
        scratch_shapes=[pltpu.VMEM((block * group_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel, grid_spec=dq_grid,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lut_flat, bits_flat, *dq_inputs)

    def from_bh(x):
        return x.reshape(bdim, h, s, d).transpose(0, 2, 1, 3)

    return from_bh(dq), from_bh(dk), from_bh(dv)


class BlockSparseAttention:
    """Callable bound to one (layout, block, causal) configuration.

    Precomputes forward/backward (row-union) LUTs host-side once; the
    kernels are then pure functions of (q, k, v) with a custom VJP.
    `group` adjacent layout rows share one grid instance (the coarse Q
    tile); `fanout` active fine K blocks are processed per grid step.
    Pass group=1, fanout=1 for one-block-at-a-time execution."""

    def __init__(self, layout, block=DEFAULT_BLOCK, causal=False,
                 sm_scale=None, group=DEFAULT_GROUP,
                 fanout=DEFAULT_FANOUT):
        layout = np.asarray(layout)
        self.layout = layout
        self.block = block
        self.causal = causal
        self.sm_scale = sm_scale
        n_q, n_k = layout.shape[1], layout.shape[2]
        while group > 1 and (n_q % group or n_k % group or group > 32):
            group //= 2
        self.group = max(1, group)
        self.fanout = max(1, fanout)
        self.lut, self.bits, self.sentinel = build_row_union_lut(
            layout, self.group, self.fanout)
        self.lut_t, self.bits_t, _ = build_row_union_lut(
            layout.transpose(0, 2, 1), self.group, self.fanout)

        @jax.custom_vjp
        def attend(q, k, v):
            scale = self.sm_scale or 1.0 / math.sqrt(q.shape[-1])
            out, _ = sparse_attention_fwd(
                q, k, v, self.lut, self.bits, self.sentinel, self.causal,
                scale, self.block, self.group, self.fanout)
            return out

        def fwd(q, k, v):
            scale = self.sm_scale or 1.0 / math.sqrt(q.shape[-1])
            return sparse_attention_fwd(
                q, k, v, self.lut, self.bits, self.sentinel, self.causal,
                scale, self.block, self.group, self.fanout)

        def bwd(res, g):
            scale = self.sm_scale or 1.0 / math.sqrt(res[0].shape[-1])
            return sparse_attention_bwd(
                res, g, self.lut, self.bits, self.lut_t, self.bits_t,
                self.sentinel, self.causal, scale, self.block, self.group,
                self.fanout)

        attend.defvjp(fwd, bwd)
        self._attend = attend

    def __call__(self, q, k, v):
        """q/k/v: [B, S, H, D] with H == layout heads, S == layout
        seq (= nQ * block)."""
        b, s, h, d = q.shape
        if h != self.layout.shape[0]:
            raise ValueError(
                f"got {h} heads, layout has {self.layout.shape[0]}")
        if s != self.layout.shape[1] * self.block:
            raise ValueError(
                f"seq {s} != layout blocks {self.layout.shape[1]} × block "
                f"{self.block}")
        return self._attend(q, k, v)
