"""Block-sparse flash attention (splash-attention-style) Pallas kernels.

TPU-native replacement for the reference's Triton block-sparse stack
(`deepspeed/ops/sparse_attention/trsrc/{matmul.tr,softmax_*.tr}` driven by
`matmul.py`/`softmax.py`): instead of materializing block-sparse score
matrices through separate SDD-matmul → sparse-softmax → DSD-matmul passes,
one fused kernel visits ONLY the active column blocks of each query-row
block, carried by a scalar-prefetched LUT, with online softmax — compute
and HBM traffic both scale with the number of active blocks.

Layout comes from `SparsityConfig.make_layout(seq)` →
[num_heads, nQ, nK] 0/1 (see `..sparse_attention.sparsity_config`).
`causal=True` applies an element-level triangular mask inside diagonal
blocks (unidirectional patterns).

**2-D block grouping**: per-grid-instance fixed cost (~6µs on v5e)
dominates one-128×128-block-per-instance execution, so the kernels
process GROUP×GROUP (default 4×4) squares of layout blocks per
instance — q AND k/v tiles are [group·128, d], the LUT lists the UNION
of active coarse column groups per coarse row group, and a per-entry
16-bit mask (`(bits >> (row·group + col)) & 1`) kills the inactive
128×128 sub-blocks elementwise. Instance count drops ~group²×; windowed
patterns' adjacent rows share columns, keeping the union tight.
"""

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LANES, NEG_INF, _causal_mask, _interpret

DEFAULT_BLOCK = 128
DEFAULT_GROUP = 4


def build_lut(layout):
    """[H, nQ, nK] 0/1 layout → (lut [H, nQ, maxA] int32, sentinel).

    lut[h, qi, :] lists the active column blocks for query-row block qi
    (padded with `sentinel` = nK). For the backward dk/dv kernel call with
    layout.transpose(0, 2, 1)."""
    layout = np.asarray(layout)
    h, n_q, n_k = layout.shape
    counts = layout.sum(axis=2)
    max_active = max(1, int(counts.max()))
    lut = np.full((h, n_q, max_active), n_k, np.int32)
    for hi in range(h):
        for qi in range(n_q):
            cols = np.nonzero(layout[hi, qi])[0]
            lut[hi, qi, :len(cols)] = cols
    return lut, n_k


def build_lut_grouped(layout, group_q, group_k):
    """Union LUT over `group_q`x`group_k` squares of layout blocks.

    Returns (lut [H, nGq, maxU] int32, bits [H, nGq, maxU] int32,
    sentinel): entry (h, g, a) is a COARSE column group (of group_k
    adjacent 128-blocks) active for at least one row of row-group g; bit
    (r*group_k + c) of bits[h, g, a] says fine row g*group_q+r is active
    for fine column col*group_k+c. Padded with sentinel/0."""
    layout = np.asarray(layout)
    h, n_q, n_k = layout.shape
    if n_q % group_q or n_k % group_k:
        raise ValueError(
            f"layout {n_q}x{n_k} not divisible by {group_q}x{group_k}")
    n_gq, n_gk = n_q // group_q, n_k // group_k
    grouped = layout.reshape(h, n_gq, group_q, n_gk, group_k)
    union = grouped.any(axis=(2, 4))          # [H, nGq, nGk]
    max_u = max(1, int(union.sum(axis=2).max()))
    lut = np.full((h, n_gq, max_u), n_gk, np.int32)
    bits = np.zeros((h, n_gq, max_u), np.int32)
    shifts = (np.arange(group_q)[:, None] * group_k
              + np.arange(group_k)[None, :])
    for hi in range(h):
        for g in range(n_gq):
            cols = np.nonzero(union[hi, g])[0]
            lut[hi, g, :len(cols)] = cols
            for a, col in enumerate(cols):
                sq = grouped[hi, g, :, col, :]      # [group_q, group_k]
                bits[hi, g, a] = int((sq.astype(np.int64) << shifts).sum())
    return lut, bits, n_gk


def _activity_mask(s, bits, base_block, group_k, transpose=False):
    """Mask score entries whose 128x128 sub-block is inactive: bit
    (r*group_k + c) of `bits` covers the sub-block at fine row r, fine
    col c of this tile. `transpose=True` swaps the roles (for the dk/dv
    kernel, whose LUT is built from the transposed layout)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // base_block
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) // base_block
    idx = cols * group_k + rows if transpose else rows * group_k + cols
    return jnp.where(((bits >> idx) & 1) == 1, s, NEG_INF)


def _sparse_fwd_kernel(lut_ref, bits_ref, q_ref, k_ref, v_ref, o_ref,
                       lse_ref, m_scr, l_scr, acc_scr,
                       *, sm_scale, causal, block_q, block_k, num_heads,
                       max_active, sentinel, group):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ai = pl.program_id(2)

    h = bh % num_heads
    n_q = pl.num_programs(1)
    ki = lut_ref[h * n_q * max_active + qi * max_active + ai]
    active = ki < sentinel

    @pl.when(ai == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _compute():
        # matmuls in the wire dtype (bf16 -> full MXU rate), fp32 accum
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * \
            sm_scale
        if group > 1:
            bits = bits_ref[h * n_q * max_active + qi * max_active + ai]
            s = _activity_mask(s, bits, block_q // group, group)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ai == pl.num_programs(2) - 1)
    def _finalize():
        # Rows with NO active blocks (dragged into a tile by the group
        # union, every score = NEG_INF) have m stuck at NEG_INF: emit 0
        # (the ungrouped kernels' l==0 convention) and poison their lse
        # to +|NEG_INF| so the backward recompute yields p = exp(s-lse)
        # = 0 instead of exp(0) garbage.
        m_row = m_scr[:, :1]
        dead = m_row <= NEG_INF * 0.5
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = jnp.where(dead, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)
        # compact [1, BQ] row-vector: 128x less HBM than lane-broadcast
        lse = jnp.where(dead, -NEG_INF, m_row + jnp.log(l_safe))
        lse_ref[0] = lse.reshape(1, -1)


def _kv_col_index(lut_ref, bh, qi, ai, *, num_heads, max_active, n_q,
                  sentinel):
    """Column block for (bh, qi, ai); inactive slots prefetch block 0."""
    h = bh % num_heads
    ki = lut_ref[h * n_q * max_active + qi * max_active + ai]
    return jax.lax.select(ki < sentinel, ki, 0)


def sparse_attention_fwd(q, k, v, lut, bits, sentinel, causal, sm_scale,
                         block_q, block_k, group):
    b, s, h, d = q.shape

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    n_q = s // block_q
    max_active = lut.shape[-1]
    lut_flat = jnp.asarray(lut.reshape(-1), jnp.int32)
    bits_flat = jnp.asarray(bits.reshape(-1), jnp.int32)

    kernel = functools.partial(
        _sparse_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_heads=h,
        max_active=max_active, sentinel=sentinel, group=group)

    kv_map = functools.partial(_kv_col_index, num_heads=h,
                               max_active=max_active, n_q=n_q,
                               sentinel=sentinel)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * h, n_q, max_active),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, ai, lref, bref: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, ai, lref, bref:
                         (bh, kv_map(lref, bh, qi, ai), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, ai, lref, bref:
                         (bh, kv_map(lref, bh, qi, ai), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, qi, ai, lref, bref: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda bh, qi, ai, lref, bref: (bh, 0, qi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lut_flat, bits_flat, qb, kb, vb)

    out4 = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out4, (qb, kb, vb, out, lse.reshape(b * h, s))


def _sparse_dkv_kernel(lut_ref, bits_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                       *, sm_scale, causal, block_q, block_k, num_heads,
                       max_active, sentinel, group):
    """Symmetric coarse tiles: k/v/dk/dv tiles cover a `group`-column
    coarse block, q/do tiles a `group`-row coarse block from the
    transposed-layout LUT; bits (transposed layout) mask inactive
    128x128 sub-blocks."""
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    ai = pl.program_id(2)
    h = bh % num_heads
    n_kv = pl.num_programs(1)
    qi = lut_ref[h * n_kv * max_active + ki * max_active + ai]
    active = qi < sentinel

    @pl.when(ai == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(active)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * \
            sm_scale
        if group > 1:
            bits = bits_ref[h * n_kv * max_active + ki * max_active + ai]
            s = _activity_mask(s, bits, block_k // group, group,
                               transpose=True)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse_ref[0].reshape(-1, 1))
        do = do_ref[0]
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * sm_scale
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ai == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sparse_dq_kernel(lut_ref, bits_ref, q_ref, k_ref, v_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, dq_scr,
                      *, sm_scale, causal, block_q, block_k, num_heads,
                      max_active, sentinel, group):
    """Row-grouped like the forward kernel."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ai = pl.program_id(2)
    h = bh % num_heads
    n_q = pl.num_programs(1)
    ki = lut_ref[h * n_q * max_active + qi * max_active + ai]
    active = ki < sentinel

    @pl.when(ai == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(active)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * \
            sm_scale
        if group > 1:
            bits = bits_ref[h * n_q * max_active + qi * max_active + ai]
            s = _activity_mask(s, bits, block_q // group, group)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse_ref[0].reshape(-1, 1))
        do = do_ref[0]
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * sm_scale
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ai == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def sparse_attention_bwd(res, g, lut, bits, lut_t, bits_t, sentinel,
                         causal, sm_scale, block_q, block_k, group):
    """block_q == block_k == group·128: all tiles are coarse on both
    sides; bits mask inactive 128x128 sub-blocks inside each tile."""
    qb, kb, vb, out, lse = res
    bh, s, d = qb.shape
    bdim = g.shape[0]
    h = bh // bdim
    do = g.transpose(0, 2, 1, 3).reshape(bh, s, d)
    lse = lse.reshape(bh, 1, s)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, s)

    n_q, n_k = s // block_q, s // block_k
    max_a = lut.shape[-1]
    max_at = lut_t.shape[-1]
    lut_flat = jnp.asarray(lut.reshape(-1), jnp.int32)
    bits_flat = jnp.asarray(bits.reshape(-1), jnp.int32)
    lut_t_flat = jnp.asarray(lut_t.reshape(-1), jnp.int32)
    bits_t_flat = jnp.asarray(bits_t.reshape(-1), jnp.int32)

    # dk/dv: grid over GROUPED column blocks; LUT lists active 128-row
    # blocks of the transposed layout.
    row_map = functools.partial(_kv_col_index, num_heads=h,
                                max_active=max_at, n_q=n_k,
                                sentinel=sentinel)
    dkv_kernel = functools.partial(
        _sparse_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_heads=h, max_active=max_at,
        sentinel=sentinel, group=group)
    dkv_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, n_k, max_at),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, ki, ai, lref, bref:
                         (b, row_map(lref, b, ki, ai), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, ki, ai, lref, bref: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, ki, ai, lref, bref: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda b, ki, ai, lref, bref:
                         (b, row_map(lref, b, ki, ai), 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, ki, ai, lref, bref:
                         (b, 0, row_map(lref, b, ki, ai))),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, ki, ai, lref, bref:
                         (b, 0, row_map(lref, b, ki, ai))),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d),
                         lambda b, ki, ai, lref, bref: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, ki, ai, lref, bref: (b, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel, grid_spec=dkv_grid,
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), kb.dtype),
            jax.ShapeDtypeStruct((bh, s, d), vb.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lut_t_flat, bits_t_flat, qb, kb, vb, do, lse, delta)

    # dq: grid over GROUPED row blocks; LUT lists active 128-col blocks.
    col_map = functools.partial(_kv_col_index, num_heads=h,
                                max_active=max_a, n_q=n_q,
                                sentinel=sentinel)
    dq_kernel = functools.partial(
        _sparse_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_heads=h, max_active=max_a,
        sentinel=sentinel, group=group)
    dq_grid = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, n_q, max_a),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, qi, ai, lref, bref: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ai, lref, bref:
                         (b, col_map(lref, b, qi, ai), 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ai, lref, bref:
                         (b, col_map(lref, b, qi, ai), 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda b, qi, ai, lref, bref: (b, qi, 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, qi, ai, lref, bref: (b, 0, qi)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b, qi, ai, lref, bref: (b, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, qi, ai, lref, bref: (b, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel, grid_spec=dq_grid,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(lut_flat, bits_flat, qb, kb, vb, do, lse, delta)

    def from_bh(x):
        return x.reshape(bdim, h, s, d).transpose(0, 2, 1, 3)

    return from_bh(dq), from_bh(dk), from_bh(dv)


class BlockSparseAttention:
    """Callable bound to one (layout, block, causal) configuration.

    Precomputes forward/backward (grouped-union) LUTs host-side once; the
    kernels are then pure functions of (q, k, v) with a custom VJP.
    `group` adjacent layout rows (and, in backward, columns) share one
    grid instance; pass group=1 to disable."""

    def __init__(self, layout, block=DEFAULT_BLOCK, causal=False,
                 sm_scale=None, group=DEFAULT_GROUP):
        layout = np.asarray(layout)
        self.layout = layout
        self.block = block
        self.causal = causal
        self.sm_scale = sm_scale
        n_q, n_k = layout.shape[1], layout.shape[2]
        # group² activity bits must fit the int32 bits array
        while group > 1 and (n_q % group or n_k % group
                             or group * group > 32):
            group //= 2
        self.group = max(1, group)
        self.lut, self.bits, self.sentinel = build_lut_grouped(
            layout, self.group, self.group)
        self.lut_t, self.bits_t, _ = build_lut_grouped(
            layout.transpose(0, 2, 1), self.group, self.group)
        self._tile = self.block * self.group

        @jax.custom_vjp
        def attend(q, k, v):
            scale = self.sm_scale or 1.0 / math.sqrt(q.shape[-1])
            out, _ = sparse_attention_fwd(
                q, k, v, self.lut, self.bits, self.sentinel, self.causal,
                scale, self._tile, self._tile, self.group)
            return out

        def fwd(q, k, v):
            scale = self.sm_scale or 1.0 / math.sqrt(q.shape[-1])
            return sparse_attention_fwd(
                q, k, v, self.lut, self.bits, self.sentinel, self.causal,
                scale, self._tile, self._tile, self.group)

        def bwd(res, g):
            scale = self.sm_scale or 1.0 / math.sqrt(res[0].shape[-1])
            return sparse_attention_bwd(
                res, g, self.lut, self.bits, self.lut_t, self.bits_t,
                self.sentinel, self.causal, scale, self._tile, self._tile,
                self.group)

        attend.defvjp(fwd, bwd)
        self._attend = attend

    def __call__(self, q, k, v):
        """q/k/v: [B, S, H, D] with H == layout heads, S == layout
        seq (= nQ * block)."""
        b, s, h, d = q.shape
        if h != self.layout.shape[0]:
            raise ValueError(
                f"got {h} heads, layout has {self.layout.shape[0]}")
        if s != self.layout.shape[1] * self.block:
            raise ValueError(
                f"seq {s} != layout blocks {self.layout.shape[1]} × block "
                f"{self.block}")
        return self._attend(q, k, v)
