from .block_sparse_attention import BlockSparseAttention, build_lut
from .flash_attention import flash_attention, flash_attention_supported
from .grouped_matmul import (grouped_matmul, grouped_matmul_supported,
                             grouped_matmul_xla)
from .optimizer import adam_flat_reference, fused_adam_flat
