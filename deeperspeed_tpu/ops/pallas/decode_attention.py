"""Paged decode attention as a Pallas TPU kernel.

The serving engine (`deeperspeed_tpu.inference`) keeps each sequence's
K/V history in fixed-size PAGES of a preallocated pool instead of one
contiguous [B, S_max, H, D] buffer — admission never has to find a
contiguous region, eviction frees exact pages, and memory scales with
tokens actually resident rather than worst-case sequence length. Decode
then needs the 1-query-row variant of the flash forward: for every
in-flight sequence, one new query attends over all its cached tokens,
reading K/V THROUGH the page table.

Contract (shared by kernel and XLA fallback):

- ``q`` [B, H, D]: one query row per sequence (the token being decoded).
- ``k_pages``/``v_pages`` [P, H, page_size, D]: the pooled cache for ONE
  layer, head-major so a model-parallel mesh shards dim 1 (heads) and
  each shard runs this kernel on its local heads unchanged (attention is
  head-independent).
- ``page_table`` [B, NP] int32: page ids of sequence b's pages in
  position order. Entries past the sequence's live pages are don't-care
  (the scheduler pads with page 0 — the pool's reserved trash page);
  their loads are masked and contribute nothing.
- ``lengths`` [B] int32: tokens valid for attention — INCLUDING the one
  being decoded (its K/V must already be written to its page). A length
  of 0 marks an inactive (padding) batch row; its output is exact zero.

Mechanics: grid (B, H, NP) with the page dimension innermost and
``arbitrary`` (it carries the online-softmax accumulation); the page
table and lengths ride as scalar prefetch
(`pltpu.PrefetchScalarGridSpec`), so the K/V BlockSpec index maps
resolve page-table indirection at DMA-issue time — the same LUT
mechanism as the compacted causal grids in `flash_attention.py`. Pages
at or past a sequence's length skip all compute (`pl.when`); the last
grid step writes ``acc / l``. No backward exists: decode is inference.

On non-TPU backends the kernel runs in interpreter mode (slow,
test-only); `paged_decode_attention` defaults to the XLA fallback there,
a gather + masked softmax with identical semantics.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams
from .flash_attention import LANES, NEG_INF, _interpret

_DIMSEM = CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))

# Test/bench observability: backend ("pallas"/"xla") of the most recent
# paged_decode_attention call — the serving tests pin which path ran.
_LAST_BACKEND = {}
_DISPATCH_LOGGED = False


def _log_first_dispatch():
    """One structured log line at the first paged-decode dispatch (see
    flash_attention._log_first_dispatch; `ops.dispatch_report()` is the
    query interface)."""
    global _DISPATCH_LOGGED
    if _DISPATCH_LOGGED:
        return
    _DISPATCH_LOGGED = True
    from ...utils.logging import logger
    logger.info("ops.dispatch decode_attention first dispatch: "
                f"backend={_LAST_BACKEND.get('decode')}")


def paged_decode_supported(head_dim, page_size, quantized=False):
    """Mosaic constraints for the real-TPU kernel: MXU-friendly head
    dim, sublane-aligned page size (int8 pools need the int8 sublane
    tile, 32). Interpret mode (CPU tests) has no tiling rules."""
    if _interpret():
        return True
    align = 32 if quantized else 8
    return head_dim in (64, 128, 256) and page_size % align == 0


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale, page_size,
                   ks_ref=None, vs_ref=None):
    """One (batch row, head, page) step of paged flash decode. With
    int8 pools (`ks_ref`/`vs_ref` scale blocks, resolved through the
    SAME page-table LUT as the data blocks), the K/V tiles dequantize
    right after the DMA — the wire moved 1 byte/element, the math runs
    fp32."""
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)
    length = len_ref[b]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0, 0].reshape(1, -1)                         # [1, D]
        k = k_ref[0, 0]                                        # [ps, D]
        if ks_ref is not None:
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * \
                ks_ref[0, 0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale     # [1, ps]
        pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + \
            p * page_size
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_scr[:, :1]                                  # [1, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        prob = jnp.exp(s - m_new)
        # masked slots would see exp(NEG_INF - m) == 0 already, except
        # when the whole page is masked and m_new == NEG_INF; zero them
        # so l stays an exact count of live probability mass
        prob = jnp.where(s <= NEG_INF * 0.5, 0.0, prob)
        l_new = alpha * l_prev + jnp.sum(prob, axis=1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        if vs_ref is not None:
            v = v_ref[0, 0].astype(jnp.float32) * \
                vs_ref[0, 0].astype(jnp.float32)[:, None]      # [ps, D]
            pv = jax.lax.dot_general(
                prob, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)            # [1, D]
        else:
            pv = jax.lax.dot_general(
                prob.astype(v_ref.dtype), v_ref[0, 0],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)            # [1, D]
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(p == n_pages - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # inactive rows (length 0) never accumulated: acc == 0 → out 0
        o_ref[0, 0] = (acc_scr[:] / l_safe).reshape(-1).astype(o_ref.dtype)


def _decode_kernel_quant(pt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                         vs_ref, o_ref, m_scr, l_scr, acc_scr, *,
                         sm_scale, page_size):
    """Positional-arg adapter for the int8 variant (pallas passes refs
    in in_specs order: data pools then scale pools)."""
    _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref, m_scr,
                   l_scr, acc_scr, sm_scale=sm_scale,
                   page_size=page_size, ks_ref=ks_ref, vs_ref=vs_ref)


def paged_decode_attention_pallas(q, k_pages, v_pages, page_table, lengths,
                                  sm_scale, k_scales=None, v_scales=None):
    B, H, D = q.shape
    page_size = k_pages.shape[2]
    NP = page_table.shape[1]
    quant = k_scales is not None
    pool_spec = pl.BlockSpec((1, 1, page_size, D),
                             lambda b, h, p, pt, ln: (pt[b, p], h, 0, 0))
    # the scale pool rides the SAME scalar-prefetch LUT that resolves
    # the data pool's page indirection — one page id, two DMAs
    scale_spec = pl.BlockSpec((1, 1, page_size),
                              lambda b, h, p, pt, ln: (pt[b, p], h, 0))
    in_specs = [
        pl.BlockSpec((1, 1, D), lambda b, h, p, pt, ln: (b, h, 0)),
        pool_spec, pool_spec,
    ]
    args = [q, k_pages, v_pages]
    kernel_fn = _decode_kernel
    if quant:
        in_specs += [scale_spec, scale_spec]
        # scale pools stay at their storage dtype (bf16) on the wire;
        # the kernel widens each [ps] tile in VMEM — a whole-pool fp32
        # cast here would materialize a pool-sized copy every step
        args += [k_scales, v_scales]
        kernel_fn = _decode_kernel_quant
    kernel = functools.partial(kernel_fn, sm_scale=sm_scale,
                               page_size=page_size)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, NP),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, D),
                                   lambda b, h, p, pt, ln: (b, h, 0)),
            scratch_shapes=[
                pltpu.VMEM((1, LANES), jnp.float32),
                pltpu.VMEM((1, LANES), jnp.float32),
                pltpu.VMEM((1, D), jnp.float32),
            ],
        ),
        compiler_params=_DIMSEM,
        interpret=_interpret(),
    )
    return call(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
                *args)


def paged_decode_attention_xla(q, k_pages, v_pages, page_table, lengths,
                               sm_scale, k_scales=None, v_scales=None):
    """Pure-XLA reference/fallback: gather the sequence's pages back
    into a contiguous [B, H, S_max, D] view and run a masked softmax.
    Identical semantics to the kernel, including exact-zero outputs for
    inactive (length 0) rows and the int8 dequant at the gather."""
    B, H, D = q.shape
    out_dtype = q.dtype
    page_size = k_pages.shape[2]
    NP = page_table.shape[1]
    k = jnp.moveaxis(k_pages[page_table], 2, 1).reshape(B, H, NP * page_size,
                                                        D)
    v = jnp.moveaxis(v_pages[page_table], 2, 1).reshape(B, H, NP * page_size,
                                                        D)
    if k_scales is not None:
        ks = jnp.moveaxis(k_scales[page_table], 2, 1).reshape(
            B, H, NP * page_size)
        vs = jnp.moveaxis(v_scales[page_table], 2, 1).reshape(
            B, H, NP * page_size)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
        q = q.astype(jnp.float32)
    s = jnp.einsum("bhd,bhsd->bhs", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    pos = jnp.arange(NP * page_size, dtype=jnp.int32)
    s = jnp.where(pos[None, None, :] < lengths[:, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    prob = jnp.exp(s - m)
    prob = jnp.where(s <= NEG_INF * 0.5, 0.0, prob)
    l = jnp.sum(prob, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhs,bhsd->bhd", (prob / l_safe).astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(out_dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           sm_scale=None, backend=None, k_scales=None,
                           v_scales=None):
    """One decode step of paged attention: ``out[b, h] = softmax(q[b, h]
    · K[b]) · V[b]`` with K/V read through ``page_table[b]`` and masked
    at ``lengths[b]``.

    ``k_scales``/``v_scales`` [P, page_size... = [P, H, page_size]]
    mark int8 pools (`inference.kv_cache.QuantizedPages`): the kernel
    dequantizes each page tile at the DMA boundary through the same
    page-table LUT; the fallback dequantizes at the gather. Kernel and
    fallback agree to float tolerance either way.

    backend: None = auto (Pallas kernel on TPU when
    `paged_decode_supported`, XLA fallback otherwise — CPU test runs
    keep XLA speed unless a test opts into the interpreter); "pallas"
    forces the kernel (interpret-mode off-TPU); "xla" forces the
    fallback.
    """
    B, H, D = q.shape
    if k_pages.shape != v_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    P, Hk, page_size, Dk = k_pages.shape
    if (Hk, Dk) != (H, D):
        raise ValueError(f"cache heads/dim {(Hk, Dk)} != query {(H, D)}")
    if page_table.ndim != 2 or page_table.shape[0] != B:
        raise ValueError(f"page_table shape {page_table.shape} must be "
                         f"[{B}, n_pages]")
    if lengths.shape != (B,):
        raise ValueError(f"lengths shape {lengths.shape} != ({B},)")
    quant = k_scales is not None
    if quant and (k_scales.shape != (P, Hk, page_size) or
                  v_scales is None or
                  v_scales.shape != (P, Hk, page_size)):
        raise ValueError(
            f"int8 pool scales must both be [{P}, {Hk}, {page_size}]; "
            f"got {getattr(k_scales, 'shape', None)} / "
            f"{getattr(v_scales, 'shape', None)}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)

    if backend is None:
        on_tpu = not _interpret()
        backend = ("pallas" if on_tpu and
                   paged_decode_supported(D, page_size, quantized=quant)
                   else "xla")
    _LAST_BACKEND["decode"] = backend
    _LAST_BACKEND["decode_kv"] = "int8" if quant else str(k_pages.dtype)
    _log_first_dispatch()
    if backend == "xla":
        return paged_decode_attention_xla(q, k_pages, v_pages, page_table,
                                          lengths, sm_scale,
                                          k_scales=k_scales,
                                          v_scales=v_scales)
    if backend != "pallas":
        raise ValueError(f"unknown paged decode backend {backend!r}")
    return paged_decode_attention_pallas(q, k_pages, v_pages, page_table,
                                         lengths, sm_scale,
                                         k_scales=k_scales,
                                         v_scales=v_scales)
