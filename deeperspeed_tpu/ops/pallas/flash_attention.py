"""Flash attention as Pallas TPU kernels.

TPU-native replacement for the reference's fused softmax/attention CUDA
kernels (`csrc/transformer/softmax_kernels.cu`,
`ds_transformer_cuda.cpp` attention path): online-softmax tiling keeps the
[S, S] score matrix out of HBM entirely — O(S) memory instead of O(S²) —
which is both the perf win (HBM bandwidth is the bottleneck) and the
long-sequence enabler.

Layout: [B, S, H, D] in, [B, S, H, D] out (kernels run on a [B*H, S, D]
view; Mosaic's last-two-dims tiling rule rules out indexing the 4-D layout
with per-head singleton blocks). Forward saves the per-row logsumexp as a
compact [BH, S] row-vector (not a lane-broadcast [.., 128] tile — 128x
less residual HBM traffic); backward recomputes probabilities blockwise
(no SxS residual).

Block sizes default to 1024x1024, auto-fitted down to the largest
128-multiple dividing the sequence length. Bigger blocks mean fewer grid
instances; per-instance fixed cost (DMA setup + kernel entry, measured
~6us/instance on v5e) dominates d=64-per-head shapes, so the fewest,
fattest instances win — 1024-blocks measured ~20% faster than 512 at
GPT-small shapes. Matmuls run at the input dtype (bf16 → full MXU rate)
with fp32 accumulation; softmax math is fp32.

Causal grids are COMPACTED (splash-attention style): instead of an
n_q x n_k grid whose upper-triangle instances are gated off in-kernel
(each still launched, still prefetching its K/V or Q/dO tiles over HBM,
still paying the ~6us fixed cost), the (qi, ki) schedule is flattened
host-side into one `arbitrary` grid dimension that enumerates ONLY the
causally-alive tiles — ~n(n+1)/2 instances for n = n_q = n_k instead of
n². Scalar-prefetch index maps (`pltpu.PrefetchScalarGridSpec` LUTs,
the splash-attention mechanism) route each flat instance to its (qi, ki)
blocks, so dead tiles generate no HBM traffic at all. See
`causal_grid_maps` for the schedule and `docs/long-context.md` for the
design.

On non-TPU backends the kernels run in interpreter mode (slow, test-only).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams

BLOCK_Q = 1024
BLOCK_K = 1024
LANES = 128  # TPU minor-dim tile; in-kernel row stats are lane-broadcast
NEG_INF = -1e30

_DIMSEM = CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))
# compacted causal grids: (batch·head, flat trapezoid) — the flat dim
# carries the per-row/-column sequential accumulation, so `arbitrary`
_DIMSEM_FLAT = CompilerParams(
    dimension_semantics=("parallel", "arbitrary"))


def _interpret():
    return jax.default_backend() not in ("tpu",) and \
        "TPU" not in str(jax.devices()[0])


def _fit_block(block, s):
    """Largest 128-multiple ≤ `block` that divides s (0 if none)."""
    for cand in range(min(block, s), 127, -128):
        if cand % 128 == 0 and s % cand == 0:
            return cand
    return 0


# ---------------------------------------------------------------------------
# compacted causal grid (trapezoidal schedule)
# ---------------------------------------------------------------------------

def causal_grid_maps(n_q, n_k, block_q, block_k, order="row"):
    """The compacted causal (qi, ki) schedule: every tile with
    ki*block_k <= qi*block_q + block_q - 1, i.e. exactly the causally
    alive blocks. Returns (qmap, kmap) int32 numpy arrays consumed as
    scalar-prefetch LUTs by the kernels' BlockSpec index maps.

    order="row" (fwd / dq): qi-major, ki ascending — each q row's
    running softmax/accumulator scratch spans one contiguous run whose
    output block stays VMEM-resident until the row finishes.
    order="col" (dkv): ki-major, qi ascending — ditto for each k
    column's dk/dv accumulators.

    For n = n_q = n_k (equal blocks) the schedule has n(n+1)/2 entries
    instead of the dense grid's n² — the compile-time-verifiable
    invariant (`_LAST_GRIDS` records what each call launched)."""
    import numpy as np
    qs, ks = [], []
    if order == "row":
        for qi in range(n_q):
            kmax = min(n_k - 1, (qi * block_q + block_q - 1) // block_k)
            for ki in range(kmax + 1):
                qs.append(qi)
                ks.append(ki)
    elif order == "col":
        for ki in range(n_k):
            for qi in range((ki * block_k) // block_q, n_q):
                qs.append(qi)
                ks.append(ki)
    else:
        raise ValueError(f"unknown order {order!r}")
    return np.asarray(qs, np.int32), np.asarray(ks, np.int32)


def causal_grid_size(s, block_q=BLOCK_Q, block_k=BLOCK_K):
    """Instances a causal flash call launches per (batch·head) at seq s
    (after block auto-fitting) — the trapezoid, not the square."""
    bq, bk = _fit_block(block_q, s), _fit_block(block_k, s)
    if not bq or not bk:
        raise ValueError(f"no block fits seq {s}")
    if s // bq == 1 and s // bk == 1:
        return 1                       # single-block specialization
    return len(causal_grid_maps(s // bq, s // bk, bq, bk)[0])


# Test/debug observability: grid of the most recent tiled pallas_call per
# kernel family ("fwd" / "dkv" / "dq"). The compaction invariant tests
# assert on this instead of re-deriving lowering internals.
_LAST_GRIDS = {}

# Ditto for dispatched block geometry: {"fwd"/"dkv"/"dq": (bq, bk)} plus
# {"fwd_variant"/"bwd_variant": "single"/"trapezoid"/"dense"} of the most
# recent call — the bench longseq rows record these in `extra` so a round
# documents WHICH geometry produced its numbers.
_LAST_BLOCKS = {}
_DISPATCH_LOGGED = False


def _log_first_dispatch():
    """One structured log line at the FIRST flash dispatch of the
    process: which block geometry / grid variant is live. Later
    dispatches update `_LAST_BLOCKS` silently — `ops.dispatch_report()`
    is the query interface; this line exists so every training log
    records the kernel configuration without anyone asking."""
    global _DISPATCH_LOGGED
    if _DISPATCH_LOGGED:
        return
    _DISPATCH_LOGGED = True
    import json

    from ...utils.logging import logger
    logger.info("ops.dispatch flash_attention first dispatch: "
                + json.dumps(_LAST_BLOCKS, default=str))


def _index_adapter(compact, kv_major=False):
    """BlockSpec index maps are written once, in dense (bh, i, j) form;
    this returns the wrapper that adapts them to the grid in use.
    Identity for dense grids. For compacted grids the flat index t
    resolves through the prefetched LUTs — (i, j) = (qi, ki) for the
    row-major fwd/dq schedules, (ki, qi) for the column-major dkv
    schedule (``kv_major``)."""
    if not compact:
        return lambda f: f
    if kv_major:
        return lambda f: lambda bh, t, qm, km: f(bh, km[t], qm[t])
    return lambda f: lambda bh, t, qm, km: f(bh, qm[t], km[t])


def _tiled_call(kernel, compact, grid, in_specs, out_specs, scratch,
                out_shape, maps):
    """One pallas_call for both grid flavors: compacted trapezoid
    (scalar-prefetch LUT grid spec) or dense. Returns (call, prefetch
    operands) — invoke as ``call(*prefetch, *inputs)``."""
    if compact:
        call_kw = dict(grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=grid, in_specs=in_specs,
            out_specs=out_specs, scratch_shapes=scratch))
        prefetch = tuple(jnp.asarray(m) for m in maps)
    else:
        call_kw = dict(grid=grid, in_specs=in_specs, out_specs=out_specs,
                       scratch_shapes=scratch)
        prefetch = ()
    call = pl.pallas_call(
        kernel, out_shape=out_shape,
        compiler_params=_DIMSEM_FLAT if compact else _DIMSEM,
        interpret=_interpret(), **call_kw)
    return call, prefetch


def flash_attention_supported(shape, block_q=BLOCK_Q, block_k=BLOCK_K):
    """Kernel constraints: seq divisible by some 128-multiple block ≤ the
    requested size, MXU-friendly head dim. Callers fall back to the XLA
    path otherwise."""
    b, s, h, d = shape
    return _fit_block(block_q, s) > 0 and _fit_block(block_k, s) > 0 and \
        d in (64, 128, 256)


def _causal_mask(s, qi, ki, block_q, block_k):
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + \
        qi * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + \
        ki * block_k
    return jnp.where(rows >= cols, s, NEG_INF)


MASK_GRAIN = 128  # layout-mask granularity (one sparsity block)


def _apply_layout_mask(s, m_ref, qi, ki, block_q, block_k):
    """Mask scores with the head's [S/128, S/128] block-activity map
    (whole map in SMEM; scalar reads take dynamic indices — the same
    mechanism as the block-sparse kernels' LUTs). Inactive 128x128
    sub-blocks of the [BQ, BK] tile get NEG_INF; the expansion uses
    static sub-block slices (no in-kernel gather/reshape needed)."""
    mq, mk = block_q // MASK_GRAIN, block_k // MASK_GRAIN
    rows = []
    for a in range(mq):
        tiles = []
        for c in range(mk):
            penalty = jnp.where(m_ref[0, qi * mq + a, ki * mk + c] > 0,
                                0.0, NEG_INF)
            tiles.append(jnp.full((MASK_GRAIN, MASK_GRAIN), penalty,
                                  jnp.float32))
        rows.append(tiles[0] if mk == 1 else
                    jnp.concatenate(tiles, axis=1))
    penalty = rows[0] if mq == 1 else jnp.concatenate(rows, axis=0)
    # additive, not select: NEG_INF + finite score stays ~NEG_INF
    return s + penalty


def _dropout_keep(seed, pid, row0, col0, shape, rate):
    """Deterministic keep-mask for in-kernel attention-probability
    dropout: a 2-round avalanche hash of (seed, batch*head, absolute
    row, absolute col). The same call sites in the backward kernels
    regenerate the exact forward mask — the Pallas analogue of the
    reference's curand Philox-offset scheme
    (`csrc/transformer/dropout_kernels.cu`). Pure int32 jnp ops
    (wrapping mul/xor/shift): lowers on Mosaic AND in interpret mode
    (pltpu.prng_* has no CPU lowering). Comparison uses the low 31 bits
    so int32 arithmetic stays sign-safe."""
    rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0) + row0
    cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1) + col0
    x = rows * (-1640531527) ^ cols * (-2048144789)   # 0x9E3779B9/0x85EBCA6B
    x = x ^ (seed + pid * (-1028477387))              # 0xC2B2AE35
    x = (x ^ ((x >> 16) & 0xFFFF)) * 0x7FEB352D
    x = (x ^ ((x >> 15) & 0x1FFFF)) * (-2073452917)   # 0x846CA68B
    x = x ^ ((x >> 16) & 0xFFFF)
    thresh = jnp.int32(int(min(max(rate, 0.0), 1.0) * 2147483647))
    return (x & 0x7FFFFFFF) >= thresh


def _apply_dropout(p, seed, pid, row0, col0, rate):
    """Scale-at-train dropout on (unnormalized) probabilities: the
    softmax denominator is computed from the UNdropped p, so this equals
    torch's dropout(softmax(s)) — dropped entries are zeroed, survivors
    scaled by 1/keep, no renormalization."""
    keep = _dropout_keep(seed, pid, row0, col0, p.shape, rate)
    return jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)


# ---------------------------------------------------------------------------
# forward — single-block specialization
# ---------------------------------------------------------------------------

CAUSAL_STRIPS = 8  # column strips for dead-sub-block exp skipping


def _head_fwd(q, k, v, bias_row, seed, pid, *, sm_scale, causal,
              use_bias, dropout_rate):
    """One head's whole-sequence attention: straight (non-online)
    softmax — no running max/denominator scratch, no alpha rescale, no
    accumulator round-trips. For causal tiles the columns are processed
    in strips so exp/sum only touch rows at or below each strip (the
    upper ~(1 - (n+1)/2n) of the triangle never reaches the VPU —
    37.5% of the softmax work at 4 strips).

    With ``use_bias`` an additive per-key row [1, S] is fused into the
    scores pre-max — the TPU equivalent of the reference's mask-taking
    fused softmax (`csrc/transformer/softmax_kernels.cu` attn_softmax
    taking attn_mask): key-padding masks never materialize [S, S].

    q/k/v [S, D]; bias_row [1, S] or None; pid keys the dropout hash
    (must match the backward's regeneration). Returns
    (o/l [S, D] fp32, lse [S, 1] fp32)."""
    s_q, s_k = q.shape[0], k.shape[0]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale        # [Sq, Sk]
    if use_bias:
        s = s + bias_row                                      # [1, Sk] bcast
    # NOTE: per-strip matmuls (skipping dead sub-blocks' MXU work) were
    # measured SLOWER than one dense matmul — ragged [S-lo, w] shapes
    # cost the MXU more than the skipped flops save. Strips only gate
    # the VPU softmax work.

    if causal and s_q == s_k and s_k % CAUSAL_STRIPS == 0:
        w = s_k // CAUSAL_STRIPS
        # per-strip masked scores + [S, 1] row maxima over ALIVE rows
        # only (1-D vectors don't lower on Mosaic; keep stats 2-D)
        strips, m_parts = [], []
        for c in range(CAUSAL_STRIPS):
            lo = c * w
            sc = s[lo:, c * w:(c + 1) * w]                   # alive rows
            rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0) + lo
            cols = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1) + lo
            sc = jnp.where(rows >= cols, sc, NEG_INF)
            strips.append(sc)
            mc = jnp.max(sc, axis=1, keepdims=True)           # [Sq-lo, 1]
            if lo:
                mc = jnp.concatenate(
                    [jnp.full((lo, 1), NEG_INF, jnp.float32), mc], axis=0)
            m_parts.append(mc)
        m = m_parts[0]
        for mc in m_parts[1:]:
            m = jnp.maximum(m, mc)                            # [Sq, 1]

        l = jnp.zeros((s_q, 1), jnp.float32)
        p_strips = []
        for c in range(CAUSAL_STRIPS):
            lo = c * w
            pc = jnp.exp(strips[c] - m[lo:])
            if use_bias:
                # a fully-masked row has m == NEG_INF and exp(s - m) == 1
                # uniformly; zero masked entries so l == 0 flags the dead
                # row (poisoned-lse convention)
                pc = jnp.where(strips[c] <= NEG_INF * 0.5, 0.0, pc)
            lc = jnp.sum(pc, axis=1, keepdims=True)
            if lo:
                lc = jnp.concatenate(
                    [jnp.zeros((lo, 1), jnp.float32), lc], axis=0)
                pc = jnp.concatenate(
                    [jnp.zeros((lo, w), jnp.float32), pc], axis=0)
            l = l + lc
            p_strips.append(pc)
        p = jnp.concatenate(p_strips, axis=1)                 # [Sq, Sk]
    else:
        if causal:
            s = _causal_mask(s, 0, 0, s_q, s_k)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        if use_bias:
            p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
        l = jnp.sum(p, axis=1, keepdims=True)
    if dropout_rate > 0.0:
        # post-l: the denominator sums the undropped probabilities
        # (torch dropout(softmax(s)) semantics). Coordinates are the
        # full-tile globals — the strips branch concatenates back to
        # full [Sq, Sk] layout first, so fwd/bwd coords agree.
        p = _apply_dropout(p, seed, pid, 0, 0, dropout_rate)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = jnp.where(l == 0.0, -NEG_INF, m + jnp.log(l_safe))
    return o / l_safe, lse


def _fwd_single_kernel(*refs, sm_scale, causal, use_bias=False,
                       dropout_rate=0.0):
    """Grid (B·H,): one head per instance (see `_head_fwd`)."""
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    o_ref, lse_ref = next(it), next(it)
    o, lse = _head_fwd(
        q_ref[0], k_ref[0], v_ref[0],
        b_ref[0] if use_bias else None,
        seed_ref[0] if dropout_rate > 0.0 else None,
        pl.program_id(0), sm_scale=sm_scale, causal=causal,
        use_bias=use_bias, dropout_rate=dropout_rate)
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = lse.reshape(1, -1)


def _fwd_single_mh_kernel(*refs, sm_scale, causal, use_bias, dropout_rate,
                          hb, h_total):
    """Grid (B, H/hb): a BLOCK of hb heads per instance. At short
    sequences the per-head tiles are tiny and the per-instance fixed
    cost dominates a (B·H,) launch; batching heads amortizes it while
    every tile stays VMEM-resident (the reference's fused short-seq
    kernel — its flagship seq-128 BERT benchmark — has the same
    batching, `csrc/transformer/softmax_kernels.cu` launches over
    batch×heads in one kernel). Dropout hash pid = global b·H + head —
    identical formula in `_bwd_single_mh_kernel`."""
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    o_ref, lse_ref = next(it), next(it)
    for j in range(hb):
        pid = pl.program_id(0) * h_total + pl.program_id(1) * hb + j
        o, lse = _head_fwd(
            q_ref[0, j], k_ref[0, j], v_ref[0, j],
            b_ref[0] if use_bias else None,
            seed_ref[0] if dropout_rate > 0.0 else None,
            pid, sm_scale=sm_scale, causal=causal,
            use_bias=use_bias, dropout_rate=dropout_rate)
        o_ref[0, j] = o.astype(o_ref.dtype)
        lse_ref[0, j] = lse.reshape(1, -1)


MH_MAX_SEQ = 256           # above this, per-head tiles amortize launches
                           # (S=512 hb=2 measured SLOWER than hb=1)
_MH_VMEM_BUDGET = 6 << 20  # conservative per-instance VMEM bound


def _mh_heads(s, d, h):
    """Heads per grid instance for the heads-batched single-block
    kernels: the largest divisor of `h` whose fwd+bwd working set
    (q/k/v/do tiles + two [S, S] fp32 score tensors + grad tiles) fits
    the VMEM budget. 1 = use the plain per-(b·h) kernels."""
    if s > MH_MAX_SEQ or h <= 1:
        return 1
    per_head = 4 * s * d * 2 + 3 * s * s * 4 + 3 * s * d * 4
    hb = max(1, min(h, _MH_VMEM_BUDGET // per_head))
    while h % hb:
        hb -= 1
    return hb


def _fwd_single(qb, kb, vb, causal, sm_scale, s, d, interpret, kbias=None,
                h=None, dropout_rate=0.0, seed=None):
    bh = qb.shape[0]
    use_bias = kbias is not None
    hb = _mh_heads(s, d, h or 1)
    if hb > 1:
        b = bh // h
        kernel = functools.partial(
            _fwd_single_mh_kernel, sm_scale=sm_scale, causal=causal,
            use_bias=use_bias, dropout_rate=dropout_rate, hb=hb,
            h_total=h)
        in_specs = [pl.BlockSpec((1, hb, s, d),
                                 lambda b, hg: (b, hg, 0, 0))] * 3
        inputs = [t.reshape(b, h, s, d) for t in (qb, kb, vb)]
        if use_bias:
            in_specs.append(pl.BlockSpec((1, 1, s),
                                         lambda b, hg: (b, 0, 0)))
            inputs.append(kbias)
        if dropout_rate > 0.0:
            in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            inputs.append(seed)
        out, lse = pl.pallas_call(
            kernel,
            grid=(b, h // hb),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, hb, s, d), lambda b, hg: (b, hg, 0, 0)),
                pl.BlockSpec((1, hb, 1, s), lambda b, hg: (b, hg, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, s, d), qb.dtype),
                jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
            ],
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(*inputs)
        return out.reshape(bh, s, d), lse.reshape(bh, 1, s)
    kernel = functools.partial(_fwd_single_kernel, sm_scale=sm_scale,
                               causal=causal, use_bias=use_bias,
                               dropout_rate=dropout_rate)
    in_specs = [pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0))] * 3
    inputs = [qb, kb, vb]
    if kbias is not None:
        # kbias is [B, 1, S]; the grid runs over B*H — index by batch
        in_specs.append(pl.BlockSpec((1, 1, s),
                                     lambda i, h=h: (i // h, 0, 0)))
        inputs.append(kbias)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(seed)
    return pl.pallas_call(
        kernel,
        grid=(bh,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda bh: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, sm_scale, causal, block_q, block_k, n_k=None,
                use_seg=False, use_mask=False, use_bias=False,
                dropout_rate=0.0, compact=False):
    it = iter(refs)
    if compact:
        qmap_ref, kmap_ref = next(it), next(it)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    sq_ref = next(it) if use_seg else None
    sk_ref = next(it) if use_seg else None
    m_ref = next(it) if use_mask else None
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    o_ref, lse_ref = next(it), next(it)
    m_scr, l_scr, acc_scr = next(it), next(it), next(it)
    if compact:
        # flat trapezoidal schedule: (qi, ki) from the prefetched LUTs;
        # the row ends at its causal k-extent, not at n_k - 1
        t = pl.program_id(1)
        qi, ki = qmap_ref[t], kmap_ref[t]
        last_k = jnp.minimum(n_k - 1,
                             (qi * block_q + block_q - 1) // block_k)
    else:
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        last_k = pl.num_programs(2) - 1

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: block row qi attends to block cols ki with
    # ki*block_k <= qi*block_q + block_q - 1. Compacted schedules only
    # ever launch such tiles, so no gate is needed there.
    run = True
    if causal and not compact:
        run = ki * block_k <= qi * block_q + (block_q - 1)
    seg_eq = None
    if use_seg:
        # [BQ, 1] vs [1, BK] segment-id equality: the elementwise mask
        # AND the block-level skip — a tile whose q and k blocks share
        # no document runs NO matmul/softmax work (the compare itself is
        # O(BQ·BK) VPU next to the O(BQ·BK·D) MXU work it gates)
        seg_eq = sq_ref[0].reshape(-1, 1) == sk_ref[0]
        run = jnp.logical_and(run, jnp.any(seg_eq))

    @pl.when(run)
    def _compute():
        # Matmuls take the inputs' native dtype (bf16 → MXU-rate) and
        # accumulate fp32; only the softmax math is explicitly fp32.
        q = q_ref[0]                                          # [BQ, D]
        k = k_ref[0]                                          # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if seg_eq is not None:
            s = jnp.where(seg_eq, s, NEG_INF)
        if m_ref is not None:
            s = _apply_layout_mask(s, m_ref, qi, ki, block_q, block_k)
        if b_ref is not None:
            s = s + b_ref[0]                                  # [1, BK] bcast

        m_prev = m_scr[:, :1]                                 # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                       # [BQ, 1]
        p = jnp.exp(s - m_new)                                # [BQ, BK]
        if seg_eq is not None or m_ref is not None or b_ref is not None:
            # rows with EVERY entry masked would otherwise see
            # exp(s - max) == 1 uniformly; zero masked entries so l==0
            # flags the dead row (poisoned-lse convention)
            p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        if dropout_rate > 0.0:
            # post-l (denominator sums undropped p); absolute tile
            # coordinates so the backward kernels regenerate this mask
            p = _apply_dropout(p, seed_ref[0], pl.program_id(0),
                               qi * block_q, ki * block_k, dropout_rate)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BQ, D]
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ki == last_k)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse row-vector [1, BQ]: the [BQ]-per-row stats transposed onto
        # the lane dim — 128x less HBM than a lane-broadcast [BQ, LANES].
        # Dead rows (no active block — possible under a layout mask) get
        # POISONED lse (+1e30) so backward's exp(s - lse) is exactly 0,
        # the block-sparse kernels' invariant.
        lse = jnp.where(l == 0.0, -NEG_INF, m_scr[:, :1] + jnp.log(l_safe))
        lse_ref[0] = lse.reshape(1, -1)


def _mask_spec(h, n_fine_q, n_fine_k, ix=lambda f: f):
    """BlockSpec for the [H, S/128, S/128] layout mask: the WHOLE
    per-head map as one SMEM block (Mosaic requires trailing block dims
    to be 8/128-multiples or full-size; scalar SMEM reads then take
    dynamic indices). `ix` adapts the index map to the grid in use
    (`_index_adapter`)."""
    return pl.BlockSpec((1, n_fine_q, n_fine_k),
                        ix(lambda bh, i, j: (bh % h, 0, 0)),
                        memory_space=pltpu.SMEM)


def _tag_residuals(out, lse):
    """Name the forward results BEFORE they fan out to the primal output
    and the custom_vjp residuals: under `jax.checkpoint` with the
    `attn_residuals` policy (`save_only_these_names(ds_attn_out,
    ds_attn_lse)`), both survive the remat boundary, so the backward
    kernels consume saved tensors and this forward kernel never re-runs
    during the backward replay.

    Inside `shard_map` with the replication check on (the SP ring
    call sites), jax 0.4.37 has no rep rule for the `name` primitive —
    the tags are dropped there and `attn_residuals` degrades to
    recompute for that region."""
    from jax.ad_checkpoint import checkpoint_name
    try:
        return (checkpoint_name(out, "ds_attn_out"),
                checkpoint_name(lse, "ds_attn_lse"))
    except NotImplementedError:
        return out, lse


def _fwd(q, k, v, causal, sm_scale, block_q=BLOCK_Q, block_k=BLOCK_K,
         layout=None, kbias=None, dropout_rate=0.0, seed=None, seg=None):
    b, s, h, d = q.shape
    block_q, block_k = _fit_block(block_q, s), _fit_block(block_k, s)

    # [B, S, H, D] → [B*H, S, D] for contiguous per-head tiles.
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    n_q, n_k = s // block_q, s // block_k

    if n_q == 1 and n_k == 1 and layout is None and seg is None:
        # whole sequence in one block: the online-softmax machinery is
        # pure overhead — run the specialized straight-softmax kernel
        _LAST_BLOCKS["fwd"] = (s, s)
        _LAST_BLOCKS["fwd_variant"] = "single"
        _log_first_dispatch()
        out, lse = _fwd_single(qb, kb, vb, causal, sm_scale, s, d,
                               _interpret(), kbias=kbias, h=h,
                               dropout_rate=dropout_rate, seed=seed)
        out, lse = _tag_residuals(out, lse)
        out4 = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
        return out4, (qb, kb, vb, out, lse.reshape(b * h, s))

    compact = causal   # causal ⇒ trapezoidal schedule (no dead launches)
    _LAST_BLOCKS["fwd"] = (block_q, block_k)
    _LAST_BLOCKS["fwd_variant"] = "trapezoid" if compact else "dense"
    _log_first_dispatch()
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, n_k=n_k,
                               use_seg=seg is not None,
                               use_mask=layout is not None,
                               use_bias=kbias is not None,
                               dropout_rate=dropout_rate,
                               compact=compact)
    if compact:
        qmap, kmap = causal_grid_maps(n_q, n_k, block_q, block_k, "row")
        grid = (b * h, len(qmap))
    else:
        qmap = kmap = None
        grid = (b * h, n_q, n_k)
    ix = _index_adapter(compact)
    in_specs = [
        pl.BlockSpec((1, block_q, d),
                     ix(lambda bh, qi, ki: (bh, qi, 0))),
        pl.BlockSpec((1, block_k, d),
                     ix(lambda bh, qi, ki: (bh, ki, 0))),
        pl.BlockSpec((1, block_k, d),
                     ix(lambda bh, qi, ki: (bh, ki, 0))),
    ]
    bias_spec = pl.BlockSpec(
        (1, 1, block_k), ix(lambda bh, qi, ki, h=h: (bh // h, 0, ki)))
    out_specs = [
        pl.BlockSpec((1, block_q, d),
                     ix(lambda bh, qi, ki: (bh, qi, 0))),
        pl.BlockSpec((1, 1, block_q),
                     ix(lambda bh, qi, ki: (bh, 0, qi))),
    ]
    inputs = [qb, kb, vb]
    if seg is not None:
        # per-token segment ids [B, 1, S]: one q-row slice and one k-row
        # slice per tile (same batch-indexed layout as the kbias row)
        in_specs.append(pl.BlockSpec(
            (1, 1, block_q), ix(lambda bh, qi, ki, h=h: (bh // h, 0, qi))))
        inputs.append(seg)
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), ix(lambda bh, qi, ki, h=h: (bh // h, 0, ki))))
        inputs.append(seg)
    if layout is not None:
        in_specs.append(_mask_spec(h, s // MASK_GRAIN, s // MASK_GRAIN,
                                   ix))
        inputs.append(layout)
    if kbias is not None:
        in_specs.append(bias_spec)
        inputs.append(kbias)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(seed)
    out_shape = [
        jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
    ]
    scratch_shapes = [
        pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
        pltpu.VMEM((block_q, LANES), jnp.float32),   # running denom
        pltpu.VMEM((block_q, d), jnp.float32),       # out accumulator
    ]
    _LAST_GRIDS["fwd"] = grid
    call, prefetch = _tiled_call(
        kernel, compact, grid, in_specs, out_specs, scratch_shapes,
        out_shape, (qmap, kmap) if compact else ())
    out, lse = call(*prefetch, *inputs)
    out, lse = _tag_residuals(out, lse)

    out4 = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out4, (qb, kb, vb, out, lse.reshape(b * h, s))


# ---------------------------------------------------------------------------
# backward — single-block specialization (fused dq/dk/dv)
# ---------------------------------------------------------------------------

def _bwd_single_kernel(*refs, sm_scale, causal, use_bias=False,
                       dropout_rate=0.0):
    """Whole-sequence tile: ONE pass computes dq, dk AND dv — the split
    dkv/dq kernels each recompute s and p, so fusing saves a full QKᵀ
    matmul, a dO·Vᵀ matmul, and an exp pass per layer. Causal tiles
    process column strips: dead sub-blocks skip exp/multiply AND their
    share of the dv/dk/dq matmul flops. With ``use_bias`` the additive
    per-key row is re-applied pre-exp (p = exp(s + bias - lse) is then
    exactly the forward's probabilities; masked entries exp to 0)."""
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    do_ref, lse_ref, delta_ref = next(it), next(it), next(it)
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dq_ref, dk_ref, dv_ref = next(it), next(it), next(it)
    dq, dk, dv = _head_bwd(
        q_ref[0], k_ref[0], v_ref[0], do_ref[0],
        lse_ref[0].reshape(-1, 1), delta_ref[0].reshape(-1, 1),
        b_ref[0] if use_bias else None,
        seed_ref[0] if dropout_rate > 0.0 else None,
        pl.program_id(0), sm_scale=sm_scale, causal=causal,
        use_bias=use_bias, dropout_rate=dropout_rate)
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_single_mh_kernel(*refs, sm_scale, causal, use_bias, dropout_rate,
                          hb, h_total):
    """Heads-batched counterpart of `_fwd_single_mh_kernel` (same pid
    formula for the dropout hash)."""
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    do_ref, lse_ref, delta_ref = next(it), next(it), next(it)
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dq_ref, dk_ref, dv_ref = next(it), next(it), next(it)
    for j in range(hb):
        pid = pl.program_id(0) * h_total + pl.program_id(1) * hb + j
        dq, dk, dv = _head_bwd(
            q_ref[0, j], k_ref[0, j], v_ref[0, j], do_ref[0, j],
            lse_ref[0, j].reshape(-1, 1),
            delta_ref[0, j].reshape(-1, 1),
            b_ref[0] if use_bias else None,
            seed_ref[0] if dropout_rate > 0.0 else None,
            pid, sm_scale=sm_scale, causal=causal, use_bias=use_bias,
            dropout_rate=dropout_rate)
        dq_ref[0, j] = dq.astype(dq_ref.dtype)
        dk_ref[0, j] = dk.astype(dk_ref.dtype)
        dv_ref[0, j] = dv.astype(dv_ref.dtype)


def _head_bwd(q, k, v, do, lse, delta, bias_row, seed, pid, *, sm_scale,
              causal, use_bias, dropout_rate):
    """One head's whole-sequence backward: recompute scores from the
    saved lse, regenerate the dropout mask at the same (pid, coords),
    and produce (dq, dk, dv) [S, D] fp32."""
    s_q, s_k = q.shape[0], k.shape[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale        # [Sq, Sk]
    if use_bias:
        s = s + bias_row                                      # [1, Sk] bcast
    dp_full = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [Sq, Sk]
    # (dense matmuls; per-strip ragged matmuls measured slower — see fwd)

    if causal and s_q == s_k and s_k % CAUSAL_STRIPS == 0:
        w = s_k // CAUSAL_STRIPS
        dq = jnp.zeros((s_q, q.shape[1]), jnp.float32)
        dk_parts, dv_parts = [], []
        for c in range(CAUSAL_STRIPS):
            lo = c * w
            sc = s[lo:, c * w:(c + 1) * w]                    # alive rows
            rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0) + lo
            cols = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1) + lo
            sc = jnp.where(rows >= cols, sc, NEG_INF)
            pc = jnp.exp(sc - lse[lo:])                       # [Sq-lo, w]
            dpc = dp_full[lo:, c * w:(c + 1) * w]
            pc_v = pc
            if dropout_rate > 0.0:
                # regenerate the forward mask at this strip's absolute
                # coordinates (rows lo.., cols c*w..)
                keep_c = _dropout_keep(seed, pid, lo, c * w, pc.shape,
                                       dropout_rate)
                inv = 1.0 / (1.0 - dropout_rate)
                pc_v = jnp.where(keep_c, pc * inv, 0.0)
                dpc = jnp.where(keep_c, dpc * inv, 0.0)
            dsc = pc * (dpc - delta[lo:]) * sm_scale
            do_alive = do[lo:]
            dv_parts.append(jax.lax.dot_general(
                pc_v.astype(do.dtype), do_alive, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))          # [w, D]
            dk_parts.append(jax.lax.dot_general(
                dsc.astype(q.dtype), q[lo:], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))          # [w, D]
            dq_c = jax.lax.dot_general(
                dsc.astype(k.dtype), k[c * w:(c + 1) * w],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [Sq-lo, D]
            if lo:
                dq_c = jnp.concatenate(
                    [jnp.zeros((lo, q.shape[1]), jnp.float32), dq_c],
                    axis=0)
            dq = dq + dq_c
        dk = jnp.concatenate(dk_parts, axis=0)
        dv = jnp.concatenate(dv_parts, axis=0)
    else:
        if causal:
            s = _causal_mask(s, 0, 0, s_q, s_k)
        p = jnp.exp(s - lse)
        p_v = p
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed, pid, 0, 0, p.shape, dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p * inv, 0.0)
            dp_full = jnp.where(keep, dp_full * inv, 0.0)
        ds = p * (dp_full - delta) * sm_scale
        dv = jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    return dq, dk, dv


def _bwd_single(qb, kb, vb, do, lse, delta, causal, sm_scale, s, d,
                interpret, kbias=None, h=None, dropout_rate=0.0,
                seed=None):
    bh = qb.shape[0]
    use_bias = kbias is not None
    hb = _mh_heads(s, d, h or 1)
    if hb > 1:
        b = bh // h
        kernel = functools.partial(
            _bwd_single_mh_kernel, sm_scale=sm_scale, causal=causal,
            use_bias=use_bias, dropout_rate=dropout_rate, hb=hb,
            h_total=h)
        in_specs = [pl.BlockSpec((1, hb, s, d),
                                 lambda b, hg: (b, hg, 0, 0))] * 4 + \
            [pl.BlockSpec((1, hb, 1, s), lambda b, hg: (b, hg, 0, 0))] * 2
        inputs = [t.reshape(b, h, s, d) for t in (qb, kb, vb, do)] + \
            [t.reshape(b, h, 1, s) for t in (lse, delta)]
        if use_bias:
            in_specs.append(pl.BlockSpec((1, 1, s),
                                         lambda b, hg: (b, 0, 0)))
            inputs.append(kbias)
        if dropout_rate > 0.0:
            in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            inputs.append(seed)
        dq, dk, dv = pl.pallas_call(
            kernel,
            grid=(b, h // hb),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((1, hb, s, d),
                                    lambda b, hg: (b, hg, 0, 0))] * 3,
            out_shape=[
                jax.ShapeDtypeStruct((b, h, s, d), qb.dtype),
                jax.ShapeDtypeStruct((b, h, s, d), kb.dtype),
                jax.ShapeDtypeStruct((b, h, s, d), vb.dtype),
            ],
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "parallel")),
            interpret=interpret,
        )(*inputs)
        return (dq.reshape(bh, s, d), dk.reshape(bh, s, d),
                dv.reshape(bh, s, d))
    kernel = functools.partial(_bwd_single_kernel, sm_scale=sm_scale,
                               causal=causal, use_bias=kbias is not None,
                               dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
        pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
        pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
        pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0)),
        pl.BlockSpec((1, 1, s), lambda bh: (bh, 0, 0)),
        pl.BlockSpec((1, 1, s), lambda bh: (bh, 0, 0)),
    ]
    inputs = [qb, kb, vb, do, lse, delta]
    if kbias is not None:
        in_specs.append(pl.BlockSpec((1, 1, s),
                                     lambda i, h=h: (i // h, 0, 0)))
        inputs.append(kbias)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(seed)
    return pl.pallas_call(
        kernel,
        grid=(bh,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, s, d), lambda bh: (bh, 0, 0))] * 3,
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
            jax.ShapeDtypeStruct((bh, s, d), kb.dtype),
            jax.ShapeDtypeStruct((bh, s, d), vb.dtype),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k, n_q=None,
                    use_seg=False, use_mask=False, use_bias=False,
                    dropout_rate=0.0, compact=False):
    it = iter(refs)
    if compact:
        qmap_ref, kmap_ref = next(it), next(it)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    do_ref, lse_ref, delta_ref = next(it), next(it), next(it)
    sq_ref = next(it) if use_seg else None
    sk_ref = next(it) if use_seg else None
    m_ref = next(it) if use_mask else None
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dk_ref, dv_ref, dk_scr, dv_scr = next(it), next(it), next(it), next(it)
    if compact:
        # column-major trapezoid: column ki starts at its first alive
        # row (the diagonal) and always ends at the bottom row
        t = pl.program_id(1)
        qi, ki = qmap_ref[t], kmap_ref[t]
        first_q = (ki * block_k) // block_q
        last_q = n_q - 1
    else:
        ki = pl.program_id(1)
        qi = pl.program_id(2)
        first_q = 0
        last_q = pl.num_programs(2) - 1

    @pl.when(qi == first_q)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal and not compact:
        run = ki * block_k <= qi * block_q + (block_q - 1)
    seg_eq = None
    if use_seg:
        # block-level document skip, mirroring the forward kernel: a
        # fully-cross-segment tile contributes zero to dk/dv
        seg_eq = sq_ref[0].reshape(-1, 1) == sk_ref[0]
        run = jnp.logical_and(run, jnp.any(seg_eq))

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                         # [BQ, D] bf16
        k = k_ref[0]                                         # [BK, D] bf16
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if seg_eq is not None:
            s = jnp.where(seg_eq, s, NEG_INF)
        if m_ref is not None:
            s = _apply_layout_mask(s, m_ref, qi, ki, block_q, block_k)
        if b_ref is not None:
            s = s + b_ref[0]                                 # [1, BK] bcast
        p = jnp.exp(s - lse_ref[0].reshape(-1, 1))           # [BQ, BK] f32
        do = do_ref[0]                                       # [BQ, D]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [BQ, BK]
        p_v = p
        if dropout_rate > 0.0:
            # note grid order (bh, ki, qi): program_id(0) is still bh
            # and the absolute (row, col) coords match the fwd tiles
            keep = _dropout_keep(seed_ref[0], pl.program_id(0),
                                 qi * block_q, ki * block_k, p.shape,
                                 dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        # dV += P_dropᵀ dO  (P quantized to the wire dtype for MXU rate,
        # matching the reference's fp16 kernel precision)
        dv_scr[:] += jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dS = P ∘ (M ∘ dO Vᵀ / keep − delta)
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * sm_scale
        # dK += dSᵀ Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == last_q)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, n_k=None,
                   use_seg=False, use_mask=False, use_bias=False,
                   dropout_rate=0.0, compact=False):
    it = iter(refs)
    if compact:
        qmap_ref, kmap_ref = next(it), next(it)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    do_ref, lse_ref, delta_ref = next(it), next(it), next(it)
    sq_ref = next(it) if use_seg else None
    sk_ref = next(it) if use_seg else None
    m_ref = next(it) if use_mask else None
    b_ref = next(it) if use_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dq_ref, dq_scr = next(it), next(it)
    if compact:
        t = pl.program_id(1)
        qi, ki = qmap_ref[t], kmap_ref[t]
        last_k = jnp.minimum(n_k - 1,
                             (qi * block_q + block_q - 1) // block_k)
    else:
        qi = pl.program_id(1)
        ki = pl.program_id(2)
        last_k = pl.num_programs(2) - 1

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal and not compact:
        run = ki * block_k <= qi * block_q + (block_q - 1)
    seg_eq = None
    if use_seg:
        seg_eq = sq_ref[0].reshape(-1, 1) == sk_ref[0]
        run = jnp.logical_and(run, jnp.any(seg_eq))

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if seg_eq is not None:
            s = jnp.where(seg_eq, s, NEG_INF)
        if m_ref is not None:
            s = _apply_layout_mask(s, m_ref, qi, ki, block_q, block_k)
        if b_ref is not None:
            s = s + b_ref[0]
        p = jnp.exp(s - lse_ref[0].reshape(-1, 1))
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref[0], pl.program_id(0),
                                 qi * block_q, ki * block_k, p.shape,
                                 dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == last_k)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(causal, sm_scale_arg, block_q, block_k, res, g, layout=None,
         kbias=None, dropout_rate=0.0, seed=None, seg=None):
    qb, kb, vb, out, lse = res
    bh, s, d = qb.shape
    block_q, block_k = _fit_block(block_q, s), _fit_block(block_k, s)
    lse = lse.reshape(bh, 1, s)     # row-vector layout, lanes = seq
    sm_scale = sm_scale_arg if sm_scale_arg is not None else \
        1.0 / math.sqrt(d)

    # g arrives as [B, S, H, D]; reshape like the saved qb.
    bdim = g.shape[0]
    h = bh // bdim
    do = g.transpose(0, 2, 1, 3).reshape(bh, s, d)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, s)                # [BH, 1, S]

    n_q, n_k = s // block_q, s // block_k
    use_seg = seg is not None
    use_mask = layout is not None
    use_bias = kbias is not None

    if n_q == 1 and n_k == 1 and not use_mask and not use_seg:
        _LAST_BLOCKS["dkv"] = _LAST_BLOCKS["dq"] = (s, s)
        _LAST_BLOCKS["bwd_variant"] = "single"
        dq, dk, dv = _bwd_single(qb, kb, vb, do, lse, delta, causal,
                                 sm_scale, s, d, _interpret(),
                                 kbias=kbias, h=h,
                                 dropout_rate=dropout_rate, seed=seed)

        def from_bh1(x):
            return x.reshape(bdim, h, s, d).transpose(0, 2, 1, 3)

        return from_bh1(dq), from_bh1(dk), from_bh1(dv)

    compact = causal   # mirror the forward's trapezoidal schedule
    _LAST_BLOCKS["dkv"] = _LAST_BLOCKS["dq"] = (block_q, block_k)
    _LAST_BLOCKS["bwd_variant"] = "trapezoid" if compact else "dense"
    dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k, n_q=n_q,
                                   use_seg=use_seg,
                                   use_mask=use_mask,
                                   use_bias=use_bias,
                                   dropout_rate=dropout_rate,
                                   compact=compact)
    if compact:
        # dkv accumulates per k column → column-major trapezoid
        dkv_qmap, dkv_kmap = causal_grid_maps(n_q, n_k, block_q, block_k,
                                              "col")
        dkv_grid = (bh, len(dkv_qmap))
    else:
        dkv_qmap = dkv_kmap = None
        dkv_grid = (bh, n_k, n_q)
    # dense dkv grid order is (bh, ki, qi) — kv_major adapter
    ixc = _index_adapter(compact, kv_major=True)
    dkv_specs = [
        pl.BlockSpec((1, block_q, d),
                     ixc(lambda bh, ki, qi: (bh, qi, 0))),
        pl.BlockSpec((1, block_k, d),
                     ixc(lambda bh, ki, qi: (bh, ki, 0))),
        pl.BlockSpec((1, block_k, d),
                     ixc(lambda bh, ki, qi: (bh, ki, 0))),
        pl.BlockSpec((1, block_q, d),
                     ixc(lambda bh, ki, qi: (bh, qi, 0))),
        pl.BlockSpec((1, 1, block_q),
                     ixc(lambda bh, ki, qi: (bh, 0, qi))),
        pl.BlockSpec((1, 1, block_q),
                     ixc(lambda bh, ki, qi: (bh, 0, qi))),
    ]
    dkv_bias_spec = pl.BlockSpec(
        (1, 1, block_k), ixc(lambda bh, ki, qi, h=h: (bh // h, 0, ki)))
    dkv_out_specs = [
        pl.BlockSpec((1, block_k, d),
                     ixc(lambda bh, ki, qi: (bh, ki, 0))),
        pl.BlockSpec((1, block_k, d),
                     ixc(lambda bh, ki, qi: (bh, ki, 0))),
    ]
    dkv_inputs = [qb, kb, vb, do, lse, delta]
    if use_seg:
        dkv_specs.append(pl.BlockSpec(
            (1, 1, block_q),
            ixc(lambda bh, ki, qi, h=h: (bh // h, 0, qi))))
        dkv_inputs.append(seg)
        dkv_specs.append(pl.BlockSpec(
            (1, 1, block_k),
            ixc(lambda bh, ki, qi, h=h: (bh // h, 0, ki))))
        dkv_inputs.append(seg)
    if use_mask:
        dkv_specs.append(_mask_spec(h, s // MASK_GRAIN, s // MASK_GRAIN,
                                    ixc))
        dkv_inputs.append(layout)
    if use_bias:
        dkv_specs.append(dkv_bias_spec)
        dkv_inputs.append(kbias)
    if dropout_rate > 0.0:
        dkv_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_inputs.append(seed)
    dkv_out_shape = [
        jax.ShapeDtypeStruct((bh, s, d), kb.dtype),
        jax.ShapeDtypeStruct((bh, s, d), vb.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    _LAST_GRIDS["dkv"] = dkv_grid
    call, prefetch = _tiled_call(
        dkv_kernel, compact, dkv_grid, dkv_specs, dkv_out_specs,
        dkv_scratch, dkv_out_shape,
        (dkv_qmap, dkv_kmap) if compact else ())
    dk, dv = call(*prefetch, *dkv_inputs)

    dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_q=block_q,
                                  block_k=block_k, n_k=n_k,
                                  use_seg=use_seg,
                                  use_mask=use_mask,
                                  use_bias=use_bias,
                                  dropout_rate=dropout_rate,
                                  compact=compact)
    if compact:
        # dq accumulates per q row → row-major trapezoid (same as fwd)
        dq_qmap, dq_kmap = causal_grid_maps(n_q, n_k, block_q, block_k,
                                            "row")
        dq_grid = (bh, len(dq_qmap))
    else:
        dq_qmap = dq_kmap = None
        dq_grid = (bh, n_q, n_k)
    ix = _index_adapter(compact)
    dq_specs = [
        pl.BlockSpec((1, block_q, d),
                     ix(lambda bh, qi, ki: (bh, qi, 0))),
        pl.BlockSpec((1, block_k, d),
                     ix(lambda bh, qi, ki: (bh, ki, 0))),
        pl.BlockSpec((1, block_k, d),
                     ix(lambda bh, qi, ki: (bh, ki, 0))),
        pl.BlockSpec((1, block_q, d),
                     ix(lambda bh, qi, ki: (bh, qi, 0))),
        pl.BlockSpec((1, 1, block_q),
                     ix(lambda bh, qi, ki: (bh, 0, qi))),
        pl.BlockSpec((1, 1, block_q),
                     ix(lambda bh, qi, ki: (bh, 0, qi))),
    ]
    dq_bias_spec = pl.BlockSpec(
        (1, 1, block_k), ix(lambda bh, qi, ki, h=h: (bh // h, 0, ki)))
    dq_out_spec = pl.BlockSpec(
        (1, block_q, d), ix(lambda bh, qi, ki: (bh, qi, 0)))
    dq_inputs = [qb, kb, vb, do, lse, delta]
    if use_seg:
        dq_specs.append(pl.BlockSpec(
            (1, 1, block_q),
            ix(lambda bh, qi, ki, h=h: (bh // h, 0, qi))))
        dq_inputs.append(seg)
        dq_specs.append(pl.BlockSpec(
            (1, 1, block_k),
            ix(lambda bh, qi, ki, h=h: (bh // h, 0, ki))))
        dq_inputs.append(seg)
    if use_mask:
        dq_specs.append(_mask_spec(h, s // MASK_GRAIN, s // MASK_GRAIN,
                                   ix))
        dq_inputs.append(layout)
    if use_bias:
        dq_specs.append(dq_bias_spec)
        dq_inputs.append(kbias)
    if dropout_rate > 0.0:
        dq_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_inputs.append(seed)
    dq_out_shape = jax.ShapeDtypeStruct((bh, s, d), qb.dtype)
    dq_scratch = [pltpu.VMEM((block_q, d), jnp.float32)]
    _LAST_GRIDS["dq"] = dq_grid
    call, prefetch = _tiled_call(
        dq_kernel, compact, dq_grid, dq_specs, dq_out_spec, dq_scratch,
        dq_out_shape, (dq_qmap, dq_kmap) if compact else ())
    dq = call(*prefetch, *dq_inputs)

    def from_bh(x):
        return x.reshape(bdim, h, s, d).transpose(0, 2, 1, 3)

    return from_bh(dq), from_bh(dk), from_bh(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=BLOCK_Q,
                    block_k=BLOCK_K, bwd_blocks=None):
    """Tiled online-softmax attention on [B, S, H, D].

    `bwd_blocks` (optional `(bwd_block_q, bwd_block_k)` tuple) gives the
    dkv/dq backward kernels their OWN block geometry: the backward
    working set is larger (q/k/v/do tiles plus lse/delta rows and fp32
    accumulators), so at ≥8k sequences the measured-best backward blocks
    are usually narrower than the forward's. None = reuse the forward
    geometry (the pre-tuning behaviour). The saved residuals (out, lse)
    are block-independent, so fwd/bwd geometry can differ freely."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, bwd_blocks):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, res = _fwd(q, k, v, causal, scale, block_q, block_k)
    return out, res


def _flash_bwd(causal, sm_scale, block_q, block_k, bwd_blocks, res, g):
    bbq, bbk = bwd_blocks if bwd_blocks is not None else (block_q, block_k)
    return _bwd(causal, sm_scale, bbq, bbk, res, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_segmented(q, k, v, segment_ids, causal=True,
                              sm_scale=None, block_q=BLOCK_Q,
                              block_k=BLOCK_K, bwd_blocks=None):
    """Flash attention over PACKED ragged batches: tokens attend only
    within their own document (`segment_ids` [B, S] int32, 0 = pad —
    see `runtime.packing`), composed with the causal mask.

    Masking is block-granular first, element-granular second: each tile
    compares its q-block and k-block segment-id slices and SKIPS the
    whole tile (no matmul, no softmax — the same `pl.when` gate as the
    dense grid's causal gating) when no id is shared; surviving tiles
    mask the stray cross-document elements to -inf. The fwd, dkv and dq
    kernels all carry the gate, so packed batches spend MXU time only on
    intra-document attention. Fully-masked rows follow the layout-mask
    kernels' poisoned-lse convention (zero output, zero grads).

    segment_ids is data, not a parameter: its cotangent is float0
    (int inputs cannot carry gradients). `bwd_blocks` as in
    `flash_attention`.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    seg3 = segment_ids.astype(jnp.int32).reshape(
        segment_ids.shape[0], 1, -1)
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k, seg=seg3)
    return out


def _flash_seg_fwd(q, k, v, segment_ids, causal, sm_scale, block_q,
                   block_k, bwd_blocks):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    seg3 = segment_ids.astype(jnp.int32).reshape(
        segment_ids.shape[0], 1, -1)
    out, res = _fwd(q, k, v, causal, scale, block_q, block_k, seg=seg3)
    return out, (res, segment_ids)


def _flash_seg_bwd(causal, sm_scale, block_q, block_k, bwd_blocks,
                   res_seg, g):
    import numpy as np
    res, segment_ids = res_seg
    seg3 = segment_ids.astype(jnp.int32).reshape(
        segment_ids.shape[0], 1, -1)
    bbq, bbk = bwd_blocks if bwd_blocks is not None else (block_q, block_k)
    dq, dk, dv = _bwd(causal, sm_scale, bbq, bbk, res, g, seg=seg3)
    return dq, dk, dv, np.zeros(segment_ids.shape, jax.dtypes.float0)


flash_attention_segmented.defvjp(_flash_seg_fwd, _flash_seg_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention_kbias(q, k, v, kbias, causal=False, sm_scale=None,
                          block_q=BLOCK_Q, block_k=BLOCK_K):
    """Flash attention with an additive PER-KEY bias fused into the
    softmax — the TPU-native form of the reference's mask-taking fused
    softmax kernel (`csrc/transformer/softmax_kernels.cu:18-140`,
    ``attn_softmax(vals, attn_mask, ...)``): key-padding / attention
    masks ride the tiled online softmax instead of materializing a
    [B, H, S, S] score tensor.

    kbias: [B, S] float32, added to every query row's scores for that
    batch (0 = keep; ~-1e30 = masked; finite values act as biases).
    Rows whose keys are ALL masked produce zero output and zero grads
    (poisoned-lse convention shared with the layout-mask kernels).

    NOT differentiable w.r.t. kbias: its cotangent is hardwired to zero
    (it is an input mask/bias, not a parameter — the reference's
    attn_mask operand has the same contract). Do NOT route a TRAINABLE
    bias (ALiBi/relative-position tables) through kbias: jax.grad would
    silently return zeros for it. Wrap such biases into the scores
    outside the kernel, or extend the bwd kernels with the
    d(kbias) = Σ_h,q p·(dp − δ) reduction first.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    kb3 = kbias.astype(jnp.float32).reshape(kbias.shape[0], 1, -1)
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k, kbias=kb3)
    return out


def _flash_kbias_fwd(q, k, v, kbias, causal, sm_scale, block_q, block_k):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    kb3 = kbias.astype(jnp.float32).reshape(kbias.shape[0], 1, -1)
    out, res = _fwd(q, k, v, causal, scale, block_q, block_k, kbias=kb3)
    return out, (res, kbias)


def _flash_kbias_bwd(causal, sm_scale, block_q, block_k, res_kb, g):
    res, kbias = res_kb
    kb3 = kbias.astype(jnp.float32).reshape(kbias.shape[0], 1, -1)
    dq, dk, dv = _bwd(causal, sm_scale, block_q, block_k, res, g,
                      kbias=kb3)
    return dq, dk, dv, jnp.zeros_like(kbias)


flash_attention_kbias.defvjp(_flash_kbias_fwd, _flash_kbias_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_train(q, k, v, kbias, seed, causal=False,
                          sm_scale=None, block_q=BLOCK_Q, block_k=BLOCK_K,
                          dropout_rate=0.0):
    """Training-mode flash attention: fused additive per-key mask AND
    in-kernel attention-probability dropout — the full fused stack of
    the reference's training transformer kernel (attn_softmax +
    attn_prob_dropout, `csrc/transformer/softmax_kernels.cu` /
    `dropout_kernels.cu`) with O(S) memory.

    kbias: [B, S] f32 additive mask/bias (see flash_attention_kbias —
    same non-differentiable contract) or None to skip the bias refs
    entirely (unmasked training pays no bias overhead).
    seed: int32 [1] array; the dropout mask is a deterministic hash of
    (seed, batch*head, row, col), so the backward pass regenerates the
    forward's mask exactly. Derive a fresh seed per step from the step
    rng. Dropout semantics are torch's dropout(softmax(s)): the
    denominator sums the undropped probabilities and survivors scale by
    1/keep. kbias and seed receive zero cotangents.
    """
    _check_dropout_rate(dropout_rate)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    kb3 = None if kbias is None else \
        kbias.astype(jnp.float32).reshape(kbias.shape[0], 1, -1)
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k, kbias=kb3,
                  dropout_rate=dropout_rate, seed=seed)
    return out


def _check_dropout_rate(rate):
    """The survivor scale 1/(1-rate) is meaningless at rate >= 1 (inf/
    NaN outputs rather than an error) and negative rates silently keep
    everything — reject both at the entry point."""
    if not 0.0 <= rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {rate}")


def _flash_train_fwd(q, k, v, kbias, seed, causal, sm_scale, block_q,
                     block_k, dropout_rate):
    _check_dropout_rate(dropout_rate)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    kb3 = None if kbias is None else \
        kbias.astype(jnp.float32).reshape(kbias.shape[0], 1, -1)
    out, res = _fwd(q, k, v, causal, scale, block_q, block_k, kbias=kb3,
                    dropout_rate=dropout_rate, seed=seed)
    return out, (res, kbias, seed)


def _flash_train_bwd(causal, sm_scale, block_q, block_k, dropout_rate,
                     res_kb, g):
    res, kbias, seed = res_kb
    kb3 = None if kbias is None else \
        kbias.astype(jnp.float32).reshape(kbias.shape[0], 1, -1)
    dq, dk, dv = _bwd(causal, sm_scale, block_q, block_k, res, g,
                      kbias=kb3, dropout_rate=dropout_rate, seed=seed)
    dkb = None if kbias is None else jnp.zeros_like(kbias)
    return dq, dk, dv, dkb, jnp.zeros_like(seed)


flash_attention_train.defvjp(_flash_train_fwd, _flash_train_bwd)


def make_masked_flash_attention(layout128, causal=False, sm_scale=None,
                                block_q=BLOCK_Q, block_k=BLOCK_K):
    """Dense-iteration flash attention honoring a STATIC 128-granular
    block layout: every tile is computed (dense-flash cost, independent
    of density) and inactive 128x128 blocks are masked to -inf — the
    exact block-sparse pattern semantics at dense-kernel throughput.

    This is the high-density arm of `SparseSelfAttention`'s auto
    dispatch: above the measured sparse-vs-dense crossover (~30% active
    blocks, docs/sparse-attention.md) iterating everything beats the
    sparse kernels' LUT/two-pass overheads.

    layout128: [H, S/128, S/128] numpy bool/int block-activity mask
    (static — baked into the compiled kernel's mask operand).
    Returns fn(q, k, v) on [B, S, H, D] with a custom VJP.
    """
    import numpy as np
    layout = jnp.asarray(np.asarray(layout128) != 0, jnp.int32)

    def check(q):
        # the SMEM mask index map clamps out-of-range blocks — mismatched
        # shapes would silently reuse wrong masks, so validate here (the
        # sparse arm raises the same way, block_sparse_attention.py:504)
        h, s = q.shape[2], q.shape[1]
        if h != layout.shape[0]:
            raise ValueError(
                f"got {h} heads, layout has {layout.shape[0]}")
        if s != layout.shape[1] * MASK_GRAIN:
            raise ValueError(
                f"got seq {s}, layout covers "
                f"{layout.shape[1] * MASK_GRAIN}")

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def fn(q, k, v):
        check(q)
        scale = sm_scale if sm_scale is not None else \
            1.0 / math.sqrt(q.shape[-1])
        out, _ = _fwd(q, k, v, causal, scale, block_q, block_k,
                      layout=layout)
        return out

    def fwd(q, k, v):
        check(q)
        scale = sm_scale if sm_scale is not None else \
            1.0 / math.sqrt(q.shape[-1])
        return _fwd(q, k, v, causal, scale, block_q, block_k,
                    layout=layout)

    def bwd(res, g):
        return _bwd(causal, sm_scale, block_q, block_k, res, g,
                    layout=layout)

    fn.defvjp(fwd, bwd)
    return fn
