"""Flash attention as Pallas TPU kernels.

TPU-native replacement for the reference's fused softmax/attention CUDA
kernels (`csrc/transformer/softmax_kernels.cu`,
`ds_transformer_cuda.cpp` attention path): online-softmax tiling keeps the
[S, S] score matrix out of HBM entirely — O(S) memory instead of O(S²) —
which is both the perf win (HBM bandwidth is the bottleneck) and the
long-sequence enabler.

Layout: [B, S, H, D] in, [B, S, H, D] out (kernels run on a [B*H, S, D]
view; Mosaic's last-two-dims tiling rule rules out indexing the 4-D layout
with per-head singleton blocks). Forward saves the per-row logsumexp as a
compact [BH, S] row-vector (not a lane-broadcast [.., 128] tile — 128x
less residual HBM traffic); backward recomputes probabilities blockwise
(no SxS residual).

Block sizes default to 1024x1024, auto-fitted down to the largest
128-multiple dividing the sequence length. Bigger blocks mean fewer grid
instances; per-instance fixed cost (DMA setup + kernel entry, measured
~6us/instance on v5e) dominates d=64-per-head shapes, so the fewest,
fattest instances win — 1024-blocks measured ~20% faster than 512 at
GPT-small shapes. Matmuls run at the input dtype (bf16 → full MXU rate)
with fp32 accumulation; softmax math is fp32.

On non-TPU backends the kernels run in interpreter mode (slow, test-only).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 1024
BLOCK_K = 1024
LANES = 128  # TPU minor-dim tile; in-kernel row stats are lane-broadcast
NEG_INF = -1e30

_DIMSEM = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _interpret():
    return jax.default_backend() not in ("tpu",) and \
        "TPU" not in str(jax.devices()[0])


def _fit_block(block, s):
    """Largest 128-multiple ≤ `block` that divides s (0 if none)."""
    for cand in range(min(block, s), 127, -128):
        if cand % 128 == 0 and s % cand == 0:
            return cand
    return 0


def flash_attention_supported(shape, block_q=BLOCK_Q, block_k=BLOCK_K):
    """Kernel constraints: seq divisible by some 128-multiple block ≤ the
    requested size, MXU-friendly head dim. Callers fall back to the XLA
    path otherwise."""
    b, s, h, d = shape
    return _fit_block(block_q, s) > 0 and _fit_block(block_k, s) > 0 and \
        d in (64, 128, 256)


def _causal_mask(s, qi, ki, block_q, block_k):
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + \
        qi * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + \
        ki * block_k
    return jnp.where(rows >= cols, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: block row qi attends to block cols ki with
    # ki*block_k <= qi*block_q + block_q - 1.
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(run)
    def _compute():
        # Matmuls take the inputs' native dtype (bf16 → MXU-rate) and
        # accumulate fp32; only the softmax math is explicitly fp32.
        q = q_ref[0]                                          # [BQ, D]
        k = k_ref[0]                                          # [BK, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale    # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)

        m_prev = m_scr[:, :1]                                 # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                       # [BQ, 1]
        p = jnp.exp(s - m_new)                                # [BQ, BK]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BQ, D]
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse row-vector [1, BQ]: the [BQ]-per-row stats transposed onto
        # the lane dim — 128x less HBM than a lane-broadcast [BQ, LANES]
        lse = m_scr[:, :1] + jnp.log(l_safe)
        lse_ref[0] = lse.reshape(1, -1)


def _fwd(q, k, v, causal, sm_scale, block_q=BLOCK_Q, block_k=BLOCK_K):
    b, s, h, d = q.shape
    block_q, block_k = _fit_block(block_q, s), _fit_block(block_k, s)

    # [B, S, H, D] → [B*H, S, D] for contiguous per-head tiles.
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    n_q, n_k = s // block_q, s // block_k
    grid = (b * h, n_q, n_k)

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),       # out accumulator
        ],
        compiler_params=_DIMSEM,
        interpret=_interpret(),
    )(qb, kb, vb)

    out4 = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out4, (qb, kb, vb, out, lse.reshape(b * h, s))


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, sm_scale, causal, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                         # [BQ, D] bf16
        k = k_ref[0]                                         # [BK, D] bf16
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [BQ, BK]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse_ref[0].reshape(-1, 1))           # [BQ, BK] f32
        do = do_ref[0]                                       # [BQ, D]
        # dV += Pᵀ dO  (P quantized to the wire dtype for MXU rate,
        # matching the reference's fp16 kernel precision)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dS = P ∘ (dO Vᵀ − delta)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [BQ, BK]
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * sm_scale
        # dK += dSᵀ Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse_ref[0].reshape(-1, 1))
        do = do_ref[0]
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0].reshape(-1, 1)) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(causal, sm_scale_arg, block_q, block_k, res, g):
    qb, kb, vb, out, lse = res
    bh, s, d = qb.shape
    block_q, block_k = _fit_block(block_q, s), _fit_block(block_k, s)
    lse = lse.reshape(bh, 1, s)     # row-vector layout, lanes = seq
    sm_scale = sm_scale_arg if sm_scale_arg is not None else \
        1.0 / math.sqrt(d)

    # g arrives as [B, S, H, D]; reshape like the saved qb.
    bdim = g.shape[0]
    h = bh // bdim
    do = g.transpose(0, 2, 1, 3).reshape(bh, s, d)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, s)                # [BH, 1, S]

    n_q, n_k = s // block_q, s // block_k

    dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, ki, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, ki, qi: (bh, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), kb.dtype),
            jax.ShapeDtypeStruct((bh, s, d), vb.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_DIMSEM,
        interpret=_interpret(),
    )(qb, kb, vb, do, lse, delta)

    dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_q=block_q,
                                  block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qb.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_DIMSEM,
        interpret=_interpret(),
    )(qb, kb, vb, do, lse, delta)

    def from_bh(x):
        return x.reshape(bdim, h, s, d).transpose(0, 2, 1, 3)

    return from_bh(dq), from_bh(dk), from_bh(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=BLOCK_Q,
                    block_k=BLOCK_K):
    """Tiled online-softmax attention on [B, S, H, D]."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, _ = _fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, res = _fwd(q, k, v, causal, scale, block_q, block_k)
    return out, res


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    return _bwd(causal, sm_scale, block_q, block_k, res, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
