"""Quantized matmuls: int8 weight-only Pallas kernel + delayed-scaling
fp8/int8 fake-quant path.

Two distinct consumers share this module (docs/quantization.md):

1. **Weight-only int8 (serving).** Decode is weight-bandwidth bound (PR 8
   measured pre-stacking the block weights as a win before any flop
   change), so storing matmul weights as int8 with per-output-channel
   fp32 scales halves the bytes every decode step streams from HBM.
   `QuantizedWeight` is a registered pytree holding ``(qval int8 [K, N],
   scale fp32 [N])``; `quant_matmul` runs ``y = (x @ qval) * scale`` with
   the dequant INSIDE the kernel (the weight tile crosses the HBM→VMEM
   boundary at 1 byte/element, widens in VMEM, accumulates fp32). The
   XLA fallback computes the identical expression — per-channel scaling
   commutes with the contraction, so kernel and fallback agree to float
   tolerance and CPU tests run at XLA speed. Inference-only: there is no
   backward (weights at rest in int8 have no master to update).

2. **Delayed-scaling fp8/int8 (training).** The dense-FFN / grouped
   expert matmuls quantize BOTH operands per step using scales derived
   from an **amax history** (TransformerEngine-style delayed scaling:
   the scale applied at step t comes from the running max of |x| over
   the previous ``history_len`` steps, so the quantize step needs no
   fresh reduction of the current tensor before the matmul). The
   history rides `EngineState.quant` as a trailing-default field (the
   sentinel `HealthState` pattern) and is checkpointed for bit-exact
   resume. The quantize is a fake-quant (quantize→dequantize with a
   straight-through estimator), so the backward pass is the ordinary
   full-precision matmul transpose — exactly the reference recipe,
   where only the forward GEMM runs low-precision.

Bootstrap: a zero amax history (step 0, or a resumed-then-extended
history) falls back to the CURRENT tensor's amax for that step, so the
first quantized step never collapses to a degenerate scale.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams
from .flash_attention import _interpret

_DIMSEM = CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))

# Test/bench observability: backend ("pallas"/"xla") of the most recent
# quant_matmul dispatch — `ops.dispatch_report()` surfaces it next to
# the flash/decode records.
_LAST_BACKEND = {}
_DISPATCH_LOGGED = False

# quantization targets per recipe: (qmax, cast dtype or None for round)
INT8_QMAX = 127.0
FP8_QMAX = 448.0      # float8_e4m3fn finite max
QUANT_RECIPES = ("int8", "fp8")


def _log_first_dispatch():
    """One structured log line at the first quant-matmul dispatch (the
    flash/decode kernels' discipline; `ops.dispatch_report()` queries)."""
    global _DISPATCH_LOGGED
    if _DISPATCH_LOGGED:
        return
    _DISPATCH_LOGGED = True
    from ...utils.logging import logger
    logger.info("ops.dispatch quant_matmul first dispatch: "
                f"backend={_LAST_BACKEND.get('quant_matmul')}")


# ---------------------------------------------------------------------------
# weight-only int8 (serving): QuantizedWeight + quant_matmul
# ---------------------------------------------------------------------------

class QuantizedWeight:
    """Int8 weight at rest + per-output-channel fp32 scales, as a pytree
    node: ``dequant = qval.astype(f32) * scale[None, :]``. Flows through
    jit/scan/stacking like any parameter leaf (its children stack/slice
    independently); the model block body dispatches matmuls on it via
    `models.gpt_neox._wmat`."""

    __slots__ = ("qval", "scale")

    def __init__(self, qval, scale):
        self.qval = qval
        self.scale = scale

    @property
    def shape(self):
        return self.qval.shape

    @property
    def ndim(self):
        return self.qval.ndim

    @property
    def dtype(self):
        return self.qval.dtype

    def dequant(self, dtype=jnp.float32):
        return (self.qval.astype(jnp.float32) *
                self.scale[..., None, :]).astype(dtype)

    def __repr__(self):
        return (f"QuantizedWeight(shape={tuple(self.qval.shape)}, "
                f"scale={tuple(self.scale.shape)})")


jax.tree_util.register_pytree_node(
    QuantizedWeight,
    lambda qw: ((qw.qval, qw.scale), None),
    lambda _, children: QuantizedWeight(*children))


def quantize_weight(w, qmax=INT8_QMAX):
    """[K, N] (or [..., K, N]) float weight → `QuantizedWeight` with
    per-output-channel symmetric scales over the contraction dim:
    ``scale[n] = max_k |w[k, n]| / 127``. Zero columns keep scale 1 (the
    quantized column is exactly zero either way)."""
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -qmax, qmax).astype(jnp.int8)
    return QuantizedWeight(q, scale)


def quant_matmul_supported(m, k, n, block_m, block_k, block_n):
    """Mosaic constraints for the real-TPU kernel: fitted blocks must
    tile the operands exactly (int8 min tile is (32, 128), fp32/bf16
    (8, 128)). Interpret mode (CPU tests) has no tiling rules."""
    if _interpret():
        return True
    return (m % block_m == 0 and k % block_k == 0 and n % block_n == 0
            and block_k % 32 == 0 and block_n % 128 == 0
            and block_m % 8 == 0)


def _fit(block, dim, align):
    """Largest `align`-multiple ≤ block dividing dim (dim itself when no
    aligned divisor exists — interpret-mode shapes)."""
    for cand in range(min(block, dim) - min(block, dim) % align, align - 1,
                      -align):
        if dim % cand == 0:
            return cand
    return dim


def _wq_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k):
    """One (bm, bn) output tile: accumulate x[bm, bk] · dequant(q[bk, bn])
    over the k grid dim in fp32 scratch, scale once at the end.

    The weight tile is read as int8 (1 byte/element over the HBM→VMEM
    wire — the whole point) and widened in VMEM; per-channel scaling
    commutes with the k-contraction so one multiply at k == n_k-1
    replaces a dequant of every tile."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    w = q_ref[:].astype(x.dtype)
    acc_ref[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _finalize():
        o_ref[:] = (acc_ref[:] * s_ref[0, :][None, :]).astype(o_ref.dtype)


def quant_matmul_pallas(x, qw, block_m=256, block_k=512, block_n=256):
    M, K = x.shape
    N = qw.qval.shape[1]
    bm, bk, bn = (_fit(block_m, M, 8), _fit(block_k, K, 32),
                  _fit(block_n, N, 128))
    grid = (M // bm, N // bn, K // bk)
    kernel = functools.partial(_wq_kernel, n_k=grid[2])
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_DIMSEM,
        interpret=_interpret(),
    )
    return call(x, qw.qval, qw.scale.reshape(1, N).astype(jnp.float32))


def quant_matmul_xla(x, qw):
    """Fallback with identical semantics: widen the int8 weight, contract
    with fp32 accumulation, apply the per-channel scale to the output
    (scaling commutes with the contraction)."""
    y = jax.lax.dot_general(
        x, qw.qval.astype(x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (y * qw.scale[None, :]).astype(x.dtype)


def quant_matmul(x, qw, backend=None, blocks=None):
    """``y[m, n] = sum_k x[m, k] · qval[k, n] · scale[n]`` — weight-only
    int8 matmul, fp32 accumulate, output in x.dtype.

    backend: None = auto (Pallas kernel on TPU when the fitted blocks
    tile the shape, XLA fallback otherwise — CPU tests keep XLA speed
    unless a test opts into the interpreter); "pallas"/"xla" force.
    blocks: optional (bm, bk, bn) override (`ops.autotune`
    `quant_matmul_blocks` feeds the measured pick).
    """
    if x.ndim != 2:
        lead = x.shape[:-1]
        y = quant_matmul(x.reshape(-1, x.shape[-1]), qw, backend=backend,
                         blocks=blocks)
        return y.reshape(lead + (y.shape[-1],))
    M, K = x.shape
    Kw, N = qw.qval.shape
    if K != Kw:
        raise ValueError(f"x contraction dim {K} != weight rows {Kw}")
    if qw.scale.shape != (N,):
        raise ValueError(f"scale shape {qw.scale.shape} != ({N},)")
    bm, bk, bn = blocks if blocks is not None else (256, 512, 256)
    if backend is None:
        on_tpu = not _interpret()
        fits = quant_matmul_supported(M, K, N, _fit(bm, M, 8),
                                      _fit(bk, K, 32), _fit(bn, N, 128))
        backend = "pallas" if on_tpu and fits else "xla"
    _LAST_BACKEND["quant_matmul"] = backend
    _log_first_dispatch()
    if backend == "xla":
        return quant_matmul_xla(x, qw)
    if backend != "pallas":
        raise ValueError(f"unknown quant_matmul backend {backend!r}")
    return quant_matmul_pallas(x, qw, bm, bk, bn)


# ---------------------------------------------------------------------------
# delayed scaling (training): amax history + fake-quant matmul
# ---------------------------------------------------------------------------

def recipe_qmax(recipe):
    if recipe == "int8":
        return INT8_QMAX
    if recipe == "fp8":
        return FP8_QMAX
    raise ValueError(
        f"unknown quantization recipe {recipe!r}; expected one of "
        f"{list(QUANT_RECIPES)}")


def scale_from_history(history, current_amax, qmax, margin=1.0):
    """Delayed-scaling scale: ``margin · max(history) / qmax``, falling
    back to the current step's amax while the history is still all-zero
    (step 0 / freshly-extended state) so the bootstrap step never
    quantizes against a degenerate scale."""
    hist_amax = jnp.max(history)
    amax = jnp.where(hist_amax > 0.0, hist_amax, current_amax)
    amax = jnp.maximum(amax, 1e-12)
    return amax * jnp.asarray(margin, jnp.float32) / qmax


def amax_history_update(history, current_amax):
    """Roll the window one step and record the current amax at slot 0."""
    return jnp.roll(history, 1).at[0].set(current_amax)


def _fake_quant(v, scale, recipe):
    """Quantize→dequantize at `scale` with a straight-through estimator:
    the forward value is the exact representable low-precision value,
    the backward is identity (the reference delayed-scaling recipe runs
    only the forward GEMM low-precision)."""
    f = v.astype(jnp.float32) / scale
    if recipe == "int8":
        dq = jnp.clip(jnp.round(f), -INT8_QMAX, INT8_QMAX) * scale
    else:
        # SATURATING cast: float8_e4m3fn has no inf, so an out-of-range
        # conversion lands NaN — and a delayed scale is stale by
        # construction (this step's amax can exceed the history's), so
        # overflow WILL happen on amax-growth steps; clamp to the
        # representable range first (the TE saturation discipline)
        f = jnp.clip(f, -FP8_QMAX, FP8_QMAX)
        dq = (f.astype(jnp.float8_e4m3fn).astype(jnp.float32)) * scale
    dq = dq.astype(v.dtype)
    return v + jax.lax.stop_gradient(dq - v)


def scaled_matmul(x, w, hist_x, hist_w, recipe, margin=1.0,
                  dim_numbers=None):
    """One delayed-scaled matmul: quantize both operands with scales from
    their amax HISTORIES, contract with fp32 accumulation, and return
    ``(y, new_hist_x, new_hist_w)`` — the histories advanced with this
    step's amaxes (amax observation is stop-gradiented; it never enters
    the differentiated graph).

    ``dim_numbers`` defaults to a plain last-dim × first-dim contraction.
    """
    qmax = recipe_qmax(recipe)
    amax_x = jax.lax.stop_gradient(
        jnp.max(jnp.abs(x.astype(jnp.float32))))
    amax_w = jax.lax.stop_gradient(
        jnp.max(jnp.abs(w.astype(jnp.float32))))
    sx = scale_from_history(hist_x, amax_x, qmax, margin)
    sw = scale_from_history(hist_w, amax_w, qmax, margin)
    xq = _fake_quant(x, sx, recipe)
    wq = _fake_quant(w, sw, recipe)
    if dim_numbers is None:
        dim_numbers = (((x.ndim - 1,), (0,)), ((), ()))
    y = jax.lax.dot_general(xq, wq, dim_numbers,
                            preferred_element_type=jnp.float32)
    return (y.astype(x.dtype),
            amax_history_update(hist_x, amax_x),
            amax_history_update(hist_w, amax_w))


def grouped_scaled_operands(x, w, hist_x, hist_w, recipe, margin=1.0):
    """Delayed-scaling fake-quant of a grouped-expert-matmul operand
    pair: `x` [R, K] (the span-packed token buffer) and `w` [E, K, N]
    (stacked expert weights) are quantized against their amax histories
    and fed UNCHANGED into `grouped_matmul` — the kernel's masking/LUT
    machinery is orthogonal to operand precision, so the sort-dispatch
    MoE engine gains the quantized forward without a second kernel.
    Returns (xq, wq, new_hist_x, new_hist_w)."""
    qmax = recipe_qmax(recipe)
    amax_x = jax.lax.stop_gradient(
        jnp.max(jnp.abs(x.astype(jnp.float32))))
    amax_w = jax.lax.stop_gradient(
        jnp.max(jnp.abs(w.astype(jnp.float32))))
    sx = scale_from_history(hist_x, amax_x, qmax, margin)
    sw = scale_from_history(hist_w, amax_w, qmax, margin)
    return (_fake_quant(x, sx, recipe), _fake_quant(w, sw, recipe),
            amax_history_update(hist_x, amax_x),
            amax_history_update(hist_w, amax_w))


# per-block dense-FFN amax state layout: 4 tensors (ffn-in x/w,
# ffn-out x/w), each with its own history row — models.gpt_neox
# threads one [4, history_len] row per layer through the block scan
# (the MoE sort-dispatch grouped path reuses the same 4-row layout:
# in-buf/in-w, out-buf/out-w)
FFN_AMAX_TENSORS = 4


def init_amax_history(num_layers, history_len,
                      n_tensors=FFN_AMAX_TENSORS):
    """Zero-initialized per-layer amax history: [L, n_tensors, H]."""
    return jnp.zeros((int(num_layers), int(n_tensors), int(history_len)),
                     jnp.float32)


def ffn_scaled_matmuls(x2d, w_in, b_in, w_out, amax_row, recipe,
                       margin=1.0, activation=jax.nn.gelu):
    """The dense-FFN pair under delayed scaling: in-proj → gelu →
    out-proj, both matmuls quantized against `amax_row` [4, H] (rows:
    in-x, in-w, out-x, out-w). Returns (y2d, new_amax_row); the output
    bias is NOT added (callers fold it after their reduce, mirroring
    the row-parallel bias discipline of the plain FFN)."""
    h, hx_in, hw_in = scaled_matmul(x2d, w_in.astype(x2d.dtype),
                                    amax_row[0], amax_row[1], recipe,
                                    margin)
    h = activation(h + b_in.astype(h.dtype))
    y, hx_out, hw_out = scaled_matmul(h, w_out.astype(h.dtype),
                                      amax_row[2], amax_row[3], recipe,
                                      margin)
    new_row = jnp.stack([hx_in, hw_in, hx_out, hw_out])
    return y, new_row
