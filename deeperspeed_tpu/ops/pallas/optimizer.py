"""Fused flat-shard optimizer kernels (reference:
`csrc/adam/multi_tensor_adam.cu` + `multi_tensor_apply.cuh` — one CUDA
kernel applying Adam across chunked tensor lists).

TPU-native shape of the same idea: ZeRO keeps each rank's optimizer
partition as ONE flat fp32 shard, so "multi-tensor apply" degenerates to a
single elementwise kernel over that shard. The Pallas kernel below reads
param/grad/m/v tiles from HBM through VMEM once and writes the three
updated arrays — one fused pass, no per-leaf kernel launches and no
intermediate HBM round-trips. Hyperparameters arrive as scalar-prefetch
operands so LR/beta changes never recompile.

The engine's default on-device path keeps the per-leaf XLA-fused update
(XLA emits the same fused elementwise kernel per parameter); this flat
variant serves the flat-partition paths (ZeRO stage-1/2 standalone
optimizers, host-offload staging buffers) where the state already lives
as one contiguous shard.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret

LANES = 128
SUBLANES = 8
_TILE = 8 * 1024  # elements per grid step (fp32: 4 arrays * 32 KiB in VMEM)


def _adam_kernel(scalars, p_ref, g_ref, m_ref, v_ref,
                 p_out, m_out, v_out, *, adam_w):
    """One VMEM tile of the flat shard: standard Adam(W) update.

    scalars: [lr, beta1, beta2, eps, weight_decay, bias_c1, bias_c2]
    (bias_c* = 1 - beta^t precomputed; 1.0 when bias correction is off).
    """
    lr = scalars[0]
    beta1, beta2 = scalars[1], scalars[2]
    eps, wd = scalars[3], scalars[4]
    bias_c1, bias_c2 = scalars[5], scalars[6]

    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    if not adam_w:
        # classic Adam applies decay through the gradient/moments
        g = g + wd * p
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * g * g
    update = (m / bias_c1) / (jnp.sqrt(v / bias_c2) + eps)
    if adam_w:
        update = update + wd * p
    p_out[...] = (p - lr * update).astype(p_out.dtype)
    m_out[...] = m
    v_out[...] = v


@functools.partial(jax.jit, static_argnames=("adam_w", "bias_correction"))
def fused_adam_flat(p, g, m, v, lr, step, *, beta1=0.9, beta2=0.999,
                    eps=1e-8, weight_decay=0.0, adam_w=True,
                    bias_correction=True):
    """Adam(W) over a flat 1-D shard → (new_p, new_m, new_v).

    `p` may be fp32 or bf16 (updated in its own dtype from the fp32 moment
    math); `m`/`v` must be fp32; `g` any float dtype. `lr`/`step` are
    traced scalars — schedules don't recompile.
    """
    n = p.shape[0]
    pad = (-n) % _TILE
    padded = n + pad

    def flat2d(x, dtype=None):
        x = x.astype(dtype) if dtype is not None else x
        if pad:
            # a full-shard copy — keep shards _TILE-aligned to avoid it
            x = jnp.pad(x, (0, pad))
        return x.reshape(padded // LANES, LANES)

    step_f = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bias_c1 = 1.0 - jnp.asarray(beta1, jnp.float32) ** step_f
        bias_c2 = 1.0 - jnp.asarray(beta2, jnp.float32) ** step_f
    else:
        bias_c1 = bias_c2 = jnp.asarray(1.0, jnp.float32)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        bias_c1, bias_c2])

    rows_per_tile = _TILE // LANES
    grid = (padded // _TILE,)
    # index_map takes (grid_idx, scalar_ref) under scalar prefetch
    spec = pl.BlockSpec((rows_per_tile, LANES), lambda i, s: (i, 0))
    out_shapes = [
        jax.ShapeDtypeStruct((padded // LANES, LANES), p.dtype),
        jax.ShapeDtypeStruct((padded // LANES, LANES), jnp.float32),
        jax.ShapeDtypeStruct((padded // LANES, LANES), jnp.float32),
    ]
    kernel = functools.partial(_adam_kernel, adam_w=adam_w)
    new_p, new_m, new_v = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=grid,
            in_specs=[spec] * 4, out_specs=[spec] * 3),
        out_shape=out_shapes,
        interpret=_interpret(),
    )(scalars, flat2d(p), flat2d(g), flat2d(m), flat2d(v))
    return (new_p.reshape(-1)[:n], new_m.reshape(-1)[:n],
            new_v.reshape(-1)[:n])


def adam_flat_reference(p, g, m, v, lr, step, *, beta1=0.9, beta2=0.999,
                        eps=1e-8, weight_decay=0.0, adam_w=True,
                        bias_correction=True):
    """Plain-jnp Adam(W) for kernel parity tests."""
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not adam_w and weight_decay != 0:
        g = g + weight_decay * p32
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    step_f = jnp.asarray(step, jnp.float32)
    c1 = 1 - beta1 ** step_f if bias_correction else 1.0
    c2 = 1 - beta2 ** step_f if bias_correction else 1.0
    update = (m / c1) / (jnp.sqrt(v / c2) + eps)
    if adam_w and weight_decay != 0:
        update = update + weight_decay * p32
    return (p32 - lr * update).astype(p.dtype), m, v
