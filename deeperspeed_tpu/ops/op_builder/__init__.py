"""Op build system (reference: `op_builder/builder.py:81`,
`op_builder/{fused_adam,fused_lamb,cpu_adam,transformer,
stochastic_transformer,sparse_attn,async_io,utils}.py`).

The reference JIT-compiles CUDA extensions through torch's cpp_extension
(or prebuilds them under ``DS_BUILD_OPS=1``). The TPU-native split is:

- **Pallas/XLA ops** (fused optimizers, transformer kernels, flash/sparse
  attention): compiled by XLA at first trace — `load()` just returns the
  Python module and `is_compatible()` probes backend/shape support.
- **Host-native ops** (CPU Adam for the offload tier, the async-IO spool
  engine): real C++ in `csrc/`, JIT-built with g++ on first `load()`
  exactly like the reference's JIT path (ctypes in place of pybind11).

`builder.load()` raises with the build log when a native op can't build;
`ds_report` renders the availability matrix (reference `env_report.py`).
"""

__all__ = [
    "OpBuilder", "FusedAdamBuilder", "FusedLambBuilder", "CPUAdamBuilder",
    "TransformerBuilder", "StochasticTransformerBuilder",
    "SparseAttnBuilder", "AsyncIOBuilder", "UtilsBuilder", "ALL_OPS",
    "get_default_compute_capabilities",
]


class OpBuilder:
    """Base builder: `name`, `is_compatible()`, `load()` (reference
    `op_builder/builder.py:81`)."""

    NAME = "op"

    @property
    def name(self):
        return self.NAME

    def absolute_name(self):
        return f"deeperspeed_tpu.ops.{self.NAME}"

    def sources(self):
        """Native source files, [] for XLA-compiled ops."""
        return []

    def is_compatible(self):
        try:
            self.load()
            return True
        except Exception:
            return False

    def load(self):
        raise NotImplementedError

    def builder(self):  # reference API (returns the torch ext builder)
        return self


class FusedAdamBuilder(OpBuilder):
    NAME = "fused_adam"

    def load(self):
        from ..adam import fused_adam
        return fused_adam


class FusedLambBuilder(OpBuilder):
    NAME = "fused_lamb"

    def load(self):
        from ..lamb import fused_lamb
        return fused_lamb


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"

    def sources(self):
        return ["csrc/adam/cpu_adam.cpp"]

    def load(self):
        from ..adam import cpu_adam_native
        cpu_adam_native._build_library()
        return cpu_adam_native


class TransformerBuilder(OpBuilder):
    NAME = "transformer"

    def load(self):
        from ..transformer import transformer
        return transformer


class StochasticTransformerBuilder(TransformerBuilder):
    NAME = "stochastic_transformer"


class SparseAttnBuilder(OpBuilder):
    NAME = "sparse_attn"

    def load(self):
        from .. import sparse_attention
        return sparse_attention


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"

    def sources(self):
        return ["csrc/aio/aio_engine.cpp"]

    def load(self):
        from ...runtime.swap_tensor import aio_engine
        if not aio_engine.AsyncIOEngine.available():
            raise RuntimeError("async_io native engine unavailable "
                               "(no g++? see build log)")
        return aio_engine


class _FlattenUtils:
    """torch's flatten/unflatten_dense_tensors equivalents on array lists
    (reference `csrc/utils/flatten_unflatten.cpp`, loaded via
    `UtilsBuilder().load()` by the engine and every ZeRO stage)."""

    @staticmethod
    def flatten(tensors):
        import numpy as np
        import jax.numpy as jnp
        if not tensors:
            return jnp.zeros((0,), jnp.float32)
        mod = jnp if any(hasattr(t, "devices") for t in tensors) else np
        return mod.concatenate([mod.ravel(mod.asarray(t))
                                for t in tensors])

    @staticmethod
    def unflatten(flat, tensors):
        import numpy as np
        sizes = [int(np.prod(np.shape(t))) for t in tensors]
        out, off = [], 0
        for t, n in zip(tensors, sizes):
            out.append(flat[off:off + n].reshape(np.shape(t)))
            off += n
        return out


class UtilsBuilder(OpBuilder):
    NAME = "utils"

    def load(self):
        return _FlattenUtils()


ALL_OPS = {
    b.NAME: b for b in (
        FusedAdamBuilder(), FusedLambBuilder(), CPUAdamBuilder(),
        TransformerBuilder(), StochasticTransformerBuilder(),
        SparseAttnBuilder(), AsyncIOBuilder(), UtilsBuilder())
}


def get_default_compute_capabilities():
    """Reference returns CUDA compute capabilities; on TPU report the
    attached device generation(s)."""
    import jax
    try:
        return sorted({getattr(d, "device_kind", str(d))
                       for d in jax.devices()})
    except Exception:
        return []
