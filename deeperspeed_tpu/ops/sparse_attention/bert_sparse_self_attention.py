"""BERT-style self-attention over the block-sparse kernel
(reference: `deepspeed/ops/sparse_attention/bert_sparse_self_attention.py:9`).

The reference subclasses `nn.Module`, projects hidden states to q/k/v with
three Linear layers and runs `SparseSelfAttention`. Functional equivalent:
`init_params` makes the projection weights, `apply` runs
proj → sparse attention → heads-merge. Drop-in for a BERT encoder layer's
attention (used by `module_inject.replace_module` when a sparse config is
supplied).
"""

import math

import jax
import jax.numpy as jnp

from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import FixedSparsityConfig


class BertSparseSelfAttention:
    """q/k/v projections + block-sparse scaled-dot-product attention."""

    def __init__(self, config, sparsity_config=None, max_seq_length=2048):
        """`config` needs `hidden_size` and `num_attention_heads`
        (reference takes the HF BertConfig)."""
        if config.hidden_size % config.num_attention_heads != 0:
            raise ValueError(
                f"hidden size {config.hidden_size} not a multiple of the "
                f"number of attention heads {config.num_attention_heads}")
        self.num_attention_heads = config.num_attention_heads
        self.attention_head_size = (config.hidden_size //
                                    config.num_attention_heads)
        self.all_head_size = (self.num_attention_heads *
                              self.attention_head_size)
        # "mul" mode: this wrapper takes a raw 0/1 keep-mask (HF user
        # convention), not the pre-additivized -10000 form
        self.sparse_self_attention = SparseSelfAttention(
            sparsity_config or FixedSparsityConfig(
                num_heads=config.num_attention_heads),
            key_padding_mask_mode="mul",
            max_seq_length=max_seq_length)

    def init_params(self, rng, dtype=jnp.float32):
        keys = jax.random.split(rng, 3)
        h, a = self.all_head_size, self.all_head_size
        scale = 1.0 / math.sqrt(h)

        def dense(key):
            return {
                "kernel": jax.random.normal(key, (h, a), dtype) * scale,
                "bias": jnp.zeros((a,), dtype),
            }

        return {"query": dense(keys[0]), "key": dense(keys[1]),
                "value": dense(keys[2])}

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_attention_heads,
                         self.attention_head_size)

    def apply(self, params, hidden_states, attention_mask=None):
        """[B, S, H*D] → [B, S, H*D] context (reference forward,
        bert_sparse_self_attention.py:52)."""
        def proj(p, x):
            return x @ p["kernel"] + p["bias"]

        q = self._split_heads(proj(params["query"], hidden_states))
        k = self._split_heads(proj(params["key"], hidden_states))
        v = self._split_heads(proj(params["value"], hidden_states))
        ctx = self.sparse_self_attention.forward(
            q, k, v, key_padding_mask=attention_mask)
        b, s = hidden_states.shape[:2]
        return ctx.reshape(b, s, self.all_head_size)

    __call__ = apply
