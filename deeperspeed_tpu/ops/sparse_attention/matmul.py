"""Standalone block-sparse MatMul op (reference:
`deepspeed/ops/sparse_attention/matmul.py:615` — Triton SDD/DSD/DDS kernels
from torch-blocksparse).

TPU-native design: instead of generated Triton kernels with per-column
load-balanced segment LUTs and spin-locks, the three modes lower to a
*batched dense matmul over the active blocks* — `[nnz, block, block]`
batches land directly on the MXU — plus gather (LUT indexing) and
`segment_sum` scatter-reduction, all of which XLA fuses and differentiates.
No locks are needed: the reduction over blocks sharing an output tile is a
deterministic `segment_sum`, not atomic accumulation.

Block-sparse tensor format (same as the reference): `[Z, nnz, block,
block]` where `nnz` enumerates the nonzero blocks of `layout
[H, n_row_blocks, n_col_blocks]` in row-major (head, row, col) order.

Modes over a logical dense [Z, H, M, K] x [Z, H, K, N]:
  - ``sdd``: sparse = dense @ dense (only active output blocks computed)
  - ``dsd``: dense = sparse @ dense
  - ``dds``: dense = dense @ sparse
``trans_a``/``trans_b`` transpose the *logical* operand; for sparse
operands this swaps the row/col roles of the layout and transposes each
stored block (no data movement until use).
"""

import numpy as np

import jax
import jax.numpy as jnp


def _layout_indices(layout):
    """layout [H, nQ, nK] 0/1 → (h, mi, ni) int32 arrays in row-major
    nonzero order — the block enumeration shared with `Softmax` and the
    reference's sparse tensor format."""
    layout = np.asarray(layout)
    if layout.ndim != 3:
        raise ValueError(f"layout must be [H, nQ, nK], got {layout.shape}")
    h, mi, ni = np.nonzero(layout)
    if h.size == 0:
        raise ValueError("layout has no nonzero blocks")
    return (h.astype(np.int32), mi.astype(np.int32), ni.astype(np.int32))


def _pad_shape(x):
    """Left-pad with singleton dims to 4: dense to [Z, H, M, N], sparse to
    [Z, nnz, block, block]."""
    while x.ndim < 4:
        x = x[None]
    return x


def _seg_reduce(x, seg, num_segments):
    """Sum [Z, nnz, ...] over axis 1 grouped by `seg` → [Z, num_segments, ...].

    Deterministic replacement for the reference kernels' lock-guarded
    accumulation across load-balanced segments."""
    moved = jnp.moveaxis(x, 1, 0)
    out = jax.ops.segment_sum(moved, jnp.asarray(seg),
                              num_segments=num_segments)
    return jnp.moveaxis(out, 0, 1)


def _take_blocks(x4, flat_idx):
    """x4 [Z, G, ...block...] gather along axis 1 → [Z, nnz, ...]."""
    return jnp.take(x4, jnp.asarray(flat_idx), axis=1)


def dense_to_sparse(x, layout, block):
    """Dense [Z, H, nQ*B, nK*B] → sparse [Z, nnz, B, B] in the layout's
    row-major nonzero block order."""
    x = jnp.asarray(x)
    h_idx, mi_idx, ni_idx = _layout_indices(layout)
    z, h, m, n = x.shape
    blocks = x.reshape(z, h, m // block, block, n // block, block)
    blocks = blocks.transpose(0, 1, 2, 4, 3, 5)   # [Z, H, nQ, nK, B, B]
    return blocks[:, h_idx, mi_idx, ni_idx]


def sparse_to_dense(x, layout, block, fill=0.0):
    """Sparse [Z, nnz, B, B] → dense [Z, H, nQ*B, nK*B]; inactive blocks
    take `fill` (use -inf-like fills for pre-softmax score matrices)."""
    x = jnp.asarray(x)
    layout = np.asarray(layout)
    h_idx, mi_idx, ni_idx = _layout_indices(layout)
    h, n_q, n_k = layout.shape
    z = x.shape[0]
    flat = jnp.full((z, h * n_q * n_k, block, block), fill, x.dtype)
    dest = (h_idx.astype(np.int64) * n_q + mi_idx) * n_k + ni_idx
    flat = flat.at[:, jnp.asarray(dest)].set(x)
    blocks = flat.reshape(z, h, n_q, n_k, block, block)
    blocks = blocks.transpose(0, 1, 2, 4, 3, 5)
    return blocks.reshape(z, h, n_q * block, n_k * block)


class MatMul:
    """Block-sparse matmul with the reference's class API
    (`matmul.py:615-745`): construct once per (layout, block, mode), call
    on `(a, b)`. Pure function of its inputs — safe to call under `jit`
    (the LUT index arrays are compile-time constants)."""

    def __init__(self, layout, block, mode, trans_a=False, trans_b=False,
                 bench=False, out_dtype=None):
        if mode not in ("sdd", "dsd", "dds"):
            raise NotImplementedError("Supported modes are: sdd, dsd, dds")
        layout = np.asarray(layout)
        self.layout = layout
        self.block = int(block)
        self.mode = mode
        self.trans_a = bool(trans_a)
        self.trans_b = bool(trans_b)
        self.spdims = layout.shape
        self.bench = bench  # accepted for API compat; timing via jax profiler
        # out_dtype=float32 keeps the fp32 accumulation in the output
        # (attention scores feeding a softmax shouldn't round to bf16)
        self.out_dtype = out_dtype
        self.h_idx, self.mi_idx, self.ni_idx = _layout_indices(layout)
        self.nnz = self.h_idx.size

    # -- mode implementations -------------------------------------------

    def _sdd(self, a, b):
        """dense a [Z,H,M,K] @ dense b [Z,H,K,N] → sparse [Z,nnz,B,B],
        computing only the active output blocks."""
        bsz = self.block
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        z, h, m, k = a.shape
        n_q, n_k = self.spdims[1], self.spdims[2]
        # A row-blocks: [Z, H*nQ, B, K]; B col-blocks as [Z, H*nK, B, K]
        # so the contraction is a clean [nnz] batch of [B,K]x[B,K]^T.
        a_blocks = a.reshape(z, h * n_q, bsz, k)
        b_blocks = jnp.swapaxes(b, -1, -2).reshape(z, h * n_k, bsz, k)
        a_sel = _take_blocks(a_blocks, self.h_idx * n_q + self.mi_idx)
        b_sel = _take_blocks(b_blocks, self.h_idx * n_k + self.ni_idx)
        return jnp.einsum(
            "znik,znjk->znij", a_sel, b_sel,
            preferred_element_type=jnp.float32).astype(
                self.out_dtype or a.dtype)

    def _dsd(self, a, b):
        """sparse a @ dense b → dense. Logical a is [Z,H,nQ*B,nK*B] (or its
        transpose when trans_a): gather b's contraction-blocks per active
        block, batch-matmul, segment-sum into output row-blocks."""
        bsz = self.block
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        z = a.shape[0]
        h, n_q, n_k = self.spdims
        n = b.shape[-1]
        if not self.trans_a:
            # contraction dim indexed by ni, output rows by mi
            contract_idx, out_idx, out_blocks = (self.ni_idx, self.mi_idx,
                                                 n_q)
            contract_blocks = n_k
            blocks = a
        else:
            # a^T: contraction over mi, output rows ni, blocks transposed
            contract_idx, out_idx, out_blocks = (self.mi_idx, self.ni_idx,
                                                 n_k)
            contract_blocks = n_q
            blocks = jnp.swapaxes(a, -1, -2)
        b_blocks = b.reshape(z, h, contract_blocks, bsz, n)
        b_blocks = b_blocks.reshape(z, h * contract_blocks, bsz, n)
        b_sel = _take_blocks(b_blocks, self.h_idx * contract_blocks
                             + contract_idx)
        prod = jnp.einsum("znab,znbc->znac", blocks, b_sel,
                          preferred_element_type=jnp.float32)
        out = _seg_reduce(prod, self.h_idx * out_blocks + out_idx,
                          h * out_blocks)
        return out.reshape(z, h, out_blocks * bsz, n).astype(
            self.out_dtype or b.dtype)

    def _dds(self, a, b):
        """dense a @ sparse b → dense. Logical b is [Z,H,nQ*B,nK*B] (or its
        transpose when trans_b)."""
        bsz = self.block
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        z = a.shape[0]
        h, n_q, n_k = self.spdims
        m = a.shape[-2]
        if not self.trans_b:
            contract_idx, out_idx, out_blocks = (self.mi_idx, self.ni_idx,
                                                 n_k)
            contract_blocks = n_q
            blocks = b
        else:
            contract_idx, out_idx, out_blocks = (self.ni_idx, self.mi_idx,
                                                 n_q)
            contract_blocks = n_k
            blocks = jnp.swapaxes(b, -1, -2)
        # a contraction-blocks: [Z, H*contract_blocks, M, B]
        a_blocks = a.reshape(z, h, m, contract_blocks, bsz)
        a_blocks = jnp.moveaxis(a_blocks, 3, 2).reshape(
            z, h * contract_blocks, m, bsz)
        a_sel = _take_blocks(a_blocks, self.h_idx * contract_blocks
                             + contract_idx)
        prod = jnp.einsum("znmb,znbc->znmc", a_sel, blocks,
                          preferred_element_type=jnp.float32)
        out = _seg_reduce(prod, self.h_idx * out_blocks + out_idx,
                          h * out_blocks)
        # [Z, H*out_blocks, M, B] → [Z, H, M, out_blocks*B]
        out = out.reshape(z, h, out_blocks, m, bsz)
        out = jnp.moveaxis(out, 2, 3).reshape(z, h, m, out_blocks * bsz)
        return out.astype(self.out_dtype or a.dtype)

    def __call__(self, a, b):
        """Applies block-sparse matmul (reference `matmul.py:695`)."""
        a = _pad_shape(jnp.asarray(a))
        b = _pad_shape(jnp.asarray(b))
        if self.mode == "sdd":
            return self._sdd(a, b)
        if self.mode == "dsd":
            return self._dsd(a, b)
        return self._dds(a, b)
