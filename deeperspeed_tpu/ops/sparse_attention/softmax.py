"""Standalone block-sparse Softmax op (reference:
`deepspeed/ops/sparse_attention/softmax.py:230` — Triton kernel
`trsrc/softmax_fwd.tr`).

Normalizes each *row* of the logical [H, nQ*B, nK*B] sparse matrix across
all of that row's active blocks, in the reference's sparse tensor format
`[Z, nnz, block, block]` (row-major (head, row-block, col-block) block
order — see `matmul._layout_indices`).

TPU-native design: the Triton kernel walks a per-row LUT; here the
cross-block row reduction is a `segment_max`/`segment_sum` over the block
axis grouped by (head, row-block), which XLA vectorizes over the lane
dimension. Autodiff supplies the backward pass (the reference hand-writes
`softmax_bwd.tr`).

Mask semantics match `softmax_fwd.tr` exactly: x*scale → +rpe →
+key-padding-mask → +attn-mask, where a "mul"-mode mask contributes
-inf where the mask is 0 and 0 elsewhere, and an "add"-mode mask is
added verbatim.
"""

import numpy as np

import jax
import jax.numpy as jnp

from .matmul import _layout_indices

_NEG = -1e30  # finite -inf stand-in: keeps fully-masked rows NaN-free


def _mask_term(mask, mode):
    mask = mask.astype(jnp.float32)
    if mode == "mul":
        return jnp.where(mask == 0, _NEG, 0.0)
    if mode == "add":
        return mask
    raise ValueError(f"mask mode must be 'add' or 'mul', got {mode!r}")


class Softmax:
    """Block-sparse softmax with the reference's class API
    (`softmax.py:230-318`). Construct once per (layout, block); call on a
    sparse tensor. Pure/functional — unlike the reference it does NOT
    mutate x in place — and safe under `jit` and `grad`."""

    def __init__(self, layout, block, bench=False):
        layout = np.asarray(layout)
        self.layout = layout
        self.block = int(block)
        self.spdims = layout.shape
        self.num_blocks = int(layout.sum())
        self.bench = bench
        self.h_idx, self.mi_idx, self.ni_idx = _layout_indices(layout)
        h, n_q, n_k = layout.shape
        # Row-group id per block: all blocks of one (head, row-block) pool
        # their columns into a single softmax domain.
        self.seg = self.h_idx.astype(np.int64) * n_q + self.mi_idx
        self.num_segments = h * n_q

    def __call__(self, x, scale=1.0, rpe=None, key_padding_mask=None,
                 attn_mask=None, key_padding_mask_mode="add",
                 attn_mask_mode="add"):
        """x: sparse [Z, nnz, B, B] (or [nnz, B, B]); rpe: dense
        [Z|1, H, S, S]; key_padding_mask: [Z, S]; attn_mask: [S, S]."""
        x = jnp.asarray(x)
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        z, nnz, bsz, _ = x.shape
        if nnz != len(self.h_idx):
            raise ValueError(
                f"expected {len(self.h_idx)} blocks, got {nnz}")
        seg = jnp.asarray(self.seg)

        f = x.astype(jnp.float32) * scale
        if rpe is not None:
            rpe = jnp.asarray(rpe)
            if rpe.ndim != 4:
                raise ValueError("rpe must be [Z|1, H, S, S]")
            # one combined gather straight to [Z|1, nnz, B, B] — chaining
            # per-axis gathers would materialize [Z, nnz, S, S]
            rows = self.mi_idx[:, None] * bsz + np.arange(bsz)[None]
            cols = self.ni_idx[:, None] * bsz + np.arange(bsz)[None]
            blk = rpe[:, jnp.asarray(self.h_idx)[:, None, None],
                      jnp.asarray(rows)[:, :, None],
                      jnp.asarray(cols)[:, None, :]]
            f = f + blk.astype(jnp.float32)
        if key_padding_mask is not None:
            kpm = _mask_term(jnp.asarray(key_padding_mask),
                             key_padding_mask_mode)      # [Z, S]
            cols = (self.ni_idx[:, None] * bsz
                    + np.arange(bsz)[None]).reshape(-1)   # [nnz*B]
            blk = jnp.take(kpm, jnp.asarray(cols), axis=1)
            f = f + blk.reshape(z, nnz, 1, bsz)
        if attn_mask is not None:
            am = _mask_term(jnp.asarray(attn_mask), attn_mask_mode)  # [S,S]
            rows = self.mi_idx[:, None] * bsz + np.arange(bsz)[None]
            cols = self.ni_idx[:, None] * bsz + np.arange(bsz)[None]
            blk = am[jnp.asarray(rows)[:, :, None],
                     jnp.asarray(cols)[:, None, :]]       # [nnz, B, B]
            f = f + blk[None]

        # Row-wise max/sum across every active block of the row.
        row_max = jnp.moveaxis(f.max(axis=-1), 1, 0)      # [nnz, Z, B]
        g_max = jax.ops.segment_max(row_max, seg,
                                    num_segments=self.num_segments)
        # Rows whose every active entry is masked to ~-inf emit zeros (the
        # dense fallback's convention; the Triton kernel emits NaN there).
        dead = g_max <= _NEG / 2                           # [nseg, Z, B]
        g_max = jnp.maximum(g_max, _NEG)  # keep exp() finite on dead rows
        m = jnp.moveaxis(jnp.take(g_max, seg, axis=0), 0, 1)  # [Z, nnz, B]
        e = jnp.exp(f - m[..., None])
        row_sum = jnp.moveaxis(e.sum(axis=-1), 1, 0)
        g_sum = jax.ops.segment_sum(row_sum, seg,
                                    num_segments=self.num_segments)
        s = jnp.moveaxis(jnp.take(g_sum, seg, axis=0), 0, 1)
        alive = ~jnp.moveaxis(jnp.take(dead, seg, axis=0), 0, 1)
        y = jnp.where(alive[..., None] & (s[..., None] > 0),
                      e / jnp.maximum(s[..., None], 1e-30),
                      0.0).astype(x.dtype)
        return y[0] if squeeze else y
