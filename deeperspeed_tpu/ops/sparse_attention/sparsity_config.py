"""Block-sparsity pattern configs (reference:
`deepspeed/ops/sparse_attention/sparsity_config.py`).

Each config produces a layout array [num_heads, num_blocks, num_blocks]
(int8, 1 = block attends) consumed by the Pallas block-sparse attention
kernel. Pattern semantics match the reference (Dense / Fixed / Variable /
BigBird / BSLongformer / LocalSlidingWindow); construction here is
vectorized numpy rather than the reference's per-element loops.
"""

import random

import numpy as np


class SparsityConfig:
    """Base: block size, head count, layout allocation/propagation."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError

    # -- shared primitives -------------------------------------------------

    @staticmethod
    def _tril(layout, h):
        layout[h] = np.tril(layout[h])
        return layout

    def _window(self, layout, h, start, end, unidirectional):
        """Dense window over block rows/cols [start, end)."""
        for row in range(start, end):
            hi = (row + 1) if unidirectional else end
            layout[h, row, start:hi] = 1
        return layout

    def _sliding(self, layout, h, width, bidirectional):
        num_blocks = layout.shape[1]
        if num_blocks < width:
            raise ValueError(
                f"Number of sliding window blocks, {width}, must be smaller "
                f"than total blocks in a row, {num_blocks}")
        w = width // 2
        rows = np.arange(num_blocks)[:, None]
        cols = np.arange(num_blocks)[None, :]
        mask = (cols >= rows - w)
        mask &= (cols <= rows + w) if bidirectional else (cols <= rows)
        layout[h] |= mask.astype(layout.dtype)
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active; kept for comparison/fallback."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern (Sparse Transformers, arXiv:1904.10509): dense local
    windows of `num_local_blocks`, plus per-window global representative
    columns (and rows if `horizontal_global_attention`)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_global_blocks > 0 and num_local_blocks % num_global_blocks:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional mode")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head")
        if num_global_blocks > 0 and num_different_global_patterns > \
                num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns "
                f"{num_different_global_patterns} cannot exceed "
                f"{num_local_blocks // num_global_blocks}")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        uni = self.attention == "unidirectional"
        for i in range(0, num_blocks, self.num_local_blocks):
            layout = self._window(layout, h, i,
                                  min(i + self.num_local_blocks, num_blocks),
                                  uni)
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        ng = self.num_global_blocks
        first_idx = self.num_local_blocks - \
            (1 + h % self.num_different_global_patterns) * ng

        end = num_blocks - (num_blocks % self.num_local_blocks)
        for i in range(first_idx, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + ng] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + ng, :] = 1
        if end < num_blocks:  # short trailing window
            start = min(end + first_idx, num_blocks - ng)
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:start + ng] = 1
            if self.horizontal_global_attention:
                layout[h, start:start + ng, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            if self.num_global_blocks > 0:
                layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + explicit global block indices +
    optional random blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != \
                    len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have the same length")
            for start, end in zip(self.global_block_indices,
                                  global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        f"global block start {start} must be < end {end}")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional mode")
        self.horizontal_global_attention = horizontal_global_attention

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} must be <= "
                f"total blocks {num_blocks}")
        for row in range(num_blocks):
            cols = random.sample(range(num_blocks), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        uni = self.attention == "unidirectional"
        start = 0
        block_size = self.local_window_blocks[-1]
        for size in self.local_window_blocks:
            layout = self._window(layout, h, start,
                                  min(start + size, num_blocks), uni)
            start += size
        for i in range(start, num_blocks, block_size):
            layout = self._window(layout, h, i,
                                  min(i + block_size, num_blocks), uni)
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(idx, idx + 1) for idx in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start, end in spans:
            if start >= num_blocks:
                continue
            end = min(end, num_blocks)
            if self.horizontal_global_attention:
                layout[h, start:end, :] = 1
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:end] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            if self.num_random_blocks > 0:
                layout = self.set_random_layout(h, layout)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (arXiv:2007.14062): random + sliding window + global ITC
    blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} must be <= "
                f"total blocks {num_blocks}")
        for row in range(num_blocks):
            pool = range(num_blocks) if self.attention == "bidirectional" \
                else range(row + 1)
            cols = random.sample(pool,
                                 min(self.num_random_blocks, len(pool)))
            layout[h, row, cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        return self._sliding(layout, h, self.num_sliding_window_blocks,
                             self.attention == "bidirectional")

    def set_global_layout_itc(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_global_blocks:
            raise ValueError(
                f"num_global_blocks {self.num_global_blocks} must be <= "
                f"total blocks {num_blocks}")
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        if self.attention == "unidirectional":
            layout = self._tril(layout, h)
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (arXiv:2004.05150): sliding window + global
    rows/columns at given indices."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != \
                    len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have the same length")
            for start, end in zip(self.global_block_indices,
                                  global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        f"global block start {start} must be < end {end}")
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def set_sliding_window_layout(self, h, layout):
        return self._sliding(layout, h, self.num_sliding_window_blocks,
                             bidirectional=True)

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(idx, idx + 1) for idx in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start, end in spans:
            if start >= num_blocks:
                continue
            end = min(end, num_blocks)
            layout[h, start:end, :] = 1
            layout[h, :, start:end] = 1
        if self.attention == "unidirectional":
            layout = self._tril(layout, h)
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding-window attention."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def set_sliding_window_layout(self, h, layout):
        return self._sliding(layout, h, self.num_sliding_window_blocks,
                             self.attention == "bidirectional")

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


MODE_TO_CONFIG = {
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "variable": VariableSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
}


def sparsity_config_from_dict(d):
    """Build a SparsityConfig from the parsed "sparse_attention" config
    block (`runtime/config.py` schema)."""
    d = dict(d)
    mode = d.pop("mode", "fixed")
    if mode not in MODE_TO_CONFIG:
        raise ValueError(f"unknown sparse attention mode {mode!r}")
    cls = MODE_TO_CONFIG[mode]
    d.setdefault("num_heads", 1)
    import inspect
    valid = set(inspect.signature(cls.__init__).parameters)
    kwargs = {k: v for k, v in d.items() if k in valid and v is not None}
    return cls(**kwargs)
