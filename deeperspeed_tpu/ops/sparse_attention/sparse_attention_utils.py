"""Utilities for integrating sparse attention into transformer models
(reference: `deepspeed/ops/sparse_attention/sparse_attention_utils.py:13`).

The reference mutates HF torch models in place (swap attention modules,
resize position embeddings, pad inputs). Functionally here: params are
pytrees, so "extend the position embedding" returns a new params tree and
"pad to block size" returns padded arrays plus the pad length.
"""

import numpy as np

import jax.numpy as jnp

from .bert_sparse_self_attention import BertSparseSelfAttention


class SparseAttentionUtils:
    """Static helpers, reference-named (sparse_attention_utils.py:13)."""

    @staticmethod
    def extend_position_embedding(position_embeddings, max_position):
        """Tile an existing [P, H] position-embedding table out to
        `max_position` rows (reference repeats the learned table to seed
        longer-context finetuning, sparse_attention_utils.py:19-66)."""
        pe = jnp.asarray(position_embeddings)
        original, hidden = pe.shape
        if max_position <= original:
            return pe[:max_position]
        reps = -(-max_position // original)  # ceil
        extended = jnp.tile(pe, (reps, 1))[:max_position]
        return extended

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Mirror of the reference helper: bump the tokenizer's model max
        length (works on HF tokenizers, which are plain Python here)."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            config, sparsity_config, max_seq_length=2048):
        """Build one `BertSparseSelfAttention` per layer for a BERT-style
        `config` (reference walks `model.bert.encoder.layer`,
        sparse_attention_utils.py:85-121; param copying is done by
        `module_inject.replace_module`, which accepts these modules)."""
        num_layers = getattr(config, "num_hidden_layers", None) or \
            getattr(config, "num_layers")
        return [BertSparseSelfAttention(config, sparsity_config,
                                        max_seq_length=max_seq_length)
                for _ in range(num_layers)]

    @staticmethod
    def pad_to_block_size(block_size, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0,
                          model_embeddings=None):
        """Pad sequence dim up to a multiple of `block_size` (reference
        sparse_attention_utils.py:151-208). Returns
        (pad_len, input_ids, attention_mask, token_type_ids, position_ids,
        inputs_embeds); padded attention-mask positions are 0 so the
        sparse kernel masks them out."""
        ref = input_ids if input_ids is not None else inputs_embeds
        if ref is None:
            raise ValueError("provide input_ids or inputs_embeds")
        seq_len = ref.shape[1]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad_ids(x, value=0):
            if x is None:
                return None
            pad = [(0, 0)] * x.ndim
            pad[1] = (0, pad_len)
            return jnp.pad(jnp.asarray(x), pad, constant_values=value)

        input_ids = pad_ids(input_ids, pad_token_id)
        attention_mask = pad_ids(attention_mask, 0)
        token_type_ids = pad_ids(token_type_ids, 0)
        if position_ids is not None:
            # continue the position sequence into the pad region
            tail = jnp.arange(seq_len, seq_len + pad_len)[None]
            tail = jnp.broadcast_to(tail,
                                    (position_ids.shape[0], pad_len))
            position_ids = jnp.concatenate(
                [jnp.asarray(position_ids), tail], axis=1)
        if inputs_embeds is not None:
            if model_embeddings is not None:
                pad_tok = jnp.full((inputs_embeds.shape[0], pad_len),
                                   pad_token_id, jnp.int32)
                pad_emb = jnp.asarray(model_embeddings)[pad_tok]
            else:
                pad_emb = jnp.zeros(
                    (inputs_embeds.shape[0], pad_len,
                     inputs_embeds.shape[2]), inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate(
                [jnp.asarray(inputs_embeds), pad_emb], axis=1)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Strip pad rows added by `pad_to_block_size` (reference
        sparse_attention_utils.py:210)."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]
