"""Sparse self-attention module (reference:
`deepspeed/ops/sparse_attention/sparse_self_attention.py:174`).

Applies a `SparsityConfig`-driven block-sparse attention to q/k/v. The
reference composes three Triton ops (SDD matmul → block softmax → DSD
matmul); here one fused Pallas kernel does all three
(`..pallas.block_sparse_attention`), falling back to a dense masked XLA
path for shapes the kernel doesn't cover.
"""

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..pallas.block_sparse_attention import BlockSparseAttention
from .matmul import MatMul
from .softmax import Softmax
from .sparsity_config import FixedSparsityConfig, SparsityConfig


def layout_to_token_mask(layout, block):
    """[H, nQ, nK] block layout → [H, S, S] boolean token mask."""
    layout = np.asarray(layout, bool)
    return np.repeat(np.repeat(layout, block, axis=1), block, axis=2)


def dense_masked_attention(q, k, v, token_mask, causal, sm_scale=None):
    """Reference/fallback path: dense attention with the block mask
    applied elementwise. [B, S, H, D] layout; token_mask is [H, S, S]
    (shared across batch) or [B, H, S, S] (e.g. with key padding)."""
    b, s, h, d = q.shape
    scale = sm_scale or 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    mask = jnp.asarray(token_mask)
    if mask.ndim == 3:
        mask = mask[None]  # [1, H, S, S]
    if causal:
        mask = jnp.logical_and(mask, jnp.tril(jnp.ones((s, s), bool)))
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    # Fully-masked rows produce uniform probs over -1e30 → NaN-free zeros.
    probs = jnp.where(mask.any(axis=-1, keepdims=True), probs, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


class SparseSelfAttention:
    """Layout-cached sparse attention, one instance per layer.

    `forward(q, k, v)` takes [B, S, H, D] (the reference takes
    [B, H, S, D]; use `transpose_inputs=True` for that layout).
    """

    # Measured sparse-vs-dense crossover on v5e (docs/sparse-attention.md):
    # BigBird at 18% active wins on the sparse kernels, Fixed at 30%
    # loses — above this active-block fraction a dense-iteration masked
    # flash kernel (cost independent of density) is faster.
    DENSE_DISPATCH_DENSITY = 0.25

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048,
                 transpose_inputs=False, dense_dispatch_density=None):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        if not isinstance(self.sparsity_config, SparsityConfig):
            raise TypeError("sparsity_config must be a SparsityConfig")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.transpose_inputs = transpose_inputs
        # auto kernel dispatch threshold; 1.0 forces the sparse kernels,
        # 0.0 forces the masked dense-flash path
        self.dense_dispatch_density = (
            self.DENSE_DISPATCH_DENSITY if dense_dispatch_density is None
            else dense_dispatch_density)
        self._cache = {}
        self._tuned = {}    # (seq, shape, dtype) -> retuned kernel

    @property
    def block(self):
        return self.sparsity_config.block

    def get_layout(self, seq_len):
        if seq_len not in self._cache:
            layout = self.sparsity_config.make_layout(seq_len)
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
            kernel = None
            block = self.block
            if seq_len % 128 == 0 and block % 128 == 0:
                # Kernel path uses 128-sized blocks; coarser layouts are
                # refined to 128 granularity.
                refine = block // 128
                fine = np.repeat(np.repeat(layout, refine, axis=1),
                                 refine, axis=2)
                density = float(np.asarray(fine, bool).mean())
                # masked flash keeps the whole per-head block map in
                # SMEM; cap it (64x64 int32 = 16KB fits, 16k-seq maps
                # don't — those are low-density anyway)
                mask_fits_smem = fine.shape[1] * fine.shape[2] * 4 <= 32768
                if density >= self.dense_dispatch_density and \
                        mask_fits_smem:
                    # auto dispatch: dense-ish layouts run the masked
                    # dense-flash kernel (same pattern semantics, cost
                    # independent of density — never slower than dense)
                    from ..pallas.flash_attention import \
                        make_masked_flash_attention
                    kernel = make_masked_flash_attention(fine,
                                                         causal=causal)
                else:
                    kernel = BlockSparseAttention(fine, block=128,
                                                  causal=causal)
            # Mid-tier for masked/rpe calls: the reference's own
            # three-op pipeline (sdd → block softmax → dsd) — compute
            # still scales with active blocks, unlike the dense fallback.
            # fp32 scores into the softmax: parity with the fused-kernel
            # path's fp32 accumulation (don't round logits to bf16)
            ops = (MatMul(layout, block, "sdd", trans_b=True,
                          out_dtype=jnp.float32),
                   Softmax(layout, block),
                   MatMul(layout, block, "dsd"))
            self._cache[seq_len] = (layout, kernel, causal, ops)
        return self._cache[seq_len]

    def _autotuned_kernel(self, s, kernel, q):
        """Swap the default-geometry sparse kernel for one built at the
        autotuner's measured (group_q, fanout) — consulted lazily at the
        first forward per (seq, call shape), because the measured pick
        needs the LIVE q/k/v shape and dtype the layer actually runs
        (`ops.autotune.sparse_block_params`; static default when
        DS_TPU_AUTOTUNE is off, so the non-tuned path pays one isinstance
        check and one dict probe)."""
        if not isinstance(kernel, BlockSparseAttention):
            return kernel     # masked dense-flash arm: nothing to tune
        from ...ops.autotune import autotune_enabled, sparse_block_params
        if not autotune_enabled():
            return kernel
        key = (s, tuple(q.shape), str(q.dtype))
        if key not in self._tuned:
            group, fanout = sparse_block_params(
                kernel.layout, tuple(q.shape), q.dtype, kernel.causal)
            if (group, fanout) == (kernel.group, kernel.fanout):
                self._tuned[key] = kernel
            else:
                self._tuned[key] = BlockSparseAttention(
                    kernel.layout, block=kernel.block,
                    causal=kernel.causal, sm_scale=kernel.sm_scale,
                    group=group, fanout=fanout)
        return self._tuned[key]

    def forward(self, query, key, value, rpe=None, key_padding_mask=None,
                attn_mask=None):
        if self.transpose_inputs:
            query, key, value = (x.transpose(0, 2, 1, 3)
                                 for x in (query, key, value))
        b, s, h, d = query.shape
        if s % self.block != 0:
            raise ValueError(
                f"sequence length {s} must be divisible by block "
                f"{self.block}")
        layout, kernel, causal, (sdd, softmax, dsd) = self.get_layout(s)

        use_kernel = (kernel is not None and d in (64, 128, 256)
                      and rpe is None and key_padding_mask is None
                      and attn_mask is None)
        if use_kernel:
            kernel = self._autotuned_kernel(s, kernel, query)
            out = kernel(query, key, value)
        else:
            # The reference's own three-op pipeline (sdd → block softmax
            # → dsd, `sparse_self_attention.py:150-170`): compute scales
            # with active blocks and every mask/rpe option applies.
            qh, kh, vh = (x.transpose(0, 2, 1, 3)
                          for x in (query, key, value))     # [B, H, S, D]
            scores = sdd(qh, kh)
            am, am_mode = attn_mask, self.attn_mask_mode
            if causal:
                # unidirectional patterns leave intra-block causality to
                # the attention mask (block layouts are block-granular);
                # fold the triangular mask into any user mask additively
                from .softmax import _NEG, _mask_term
                tril = jnp.where(
                    jnp.tril(jnp.ones((s, s), jnp.bool_)), 0.0, _NEG)
                if am is not None:
                    am = _mask_term(jnp.asarray(am), am_mode) + tril
                else:
                    am = tril
                am_mode = "add"
            if (key_padding_mask is not None
                    and self.key_padding_mask_mode == "add"
                    and not jnp.issubdtype(
                        jnp.asarray(key_padding_mask).dtype,
                        jnp.floating)):
                raise ValueError(
                    "bool/int key_padding_mask with mode 'add' looks like "
                    "a 0/1 keep-mask: pass an additive float mask (e.g. "
                    "-1e4 on padded keys), or use "
                    "key_padding_mask_mode='mul' for keep-masks")
            probs = softmax(
                scores, scale=1.0 / math.sqrt(d), rpe=rpe,
                key_padding_mask=key_padding_mask, attn_mask=am,
                key_padding_mask_mode=self.key_padding_mask_mode,
                attn_mask_mode=am_mode)
            out = dsd(probs, vh).transpose(0, 2, 1, 3).astype(query.dtype)
        if self.transpose_inputs:
            out = out.transpose(0, 2, 1, 3)
        return out

    __call__ = forward
