"""Sparse self-attention module (reference:
`deepspeed/ops/sparse_attention/sparse_self_attention.py:174`).

Applies a `SparsityConfig`-driven block-sparse attention to q/k/v. The
reference composes three Triton ops (SDD matmul → block softmax → DSD
matmul); here one fused Pallas kernel does all three
(`..pallas.block_sparse_attention`), falling back to a dense masked XLA
path for shapes the kernel doesn't cover.
"""

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..pallas.block_sparse_attention import BlockSparseAttention
from .sparsity_config import FixedSparsityConfig, SparsityConfig


def layout_to_token_mask(layout, block):
    """[H, nQ, nK] block layout → [H, S, S] boolean token mask."""
    layout = np.asarray(layout, bool)
    return np.repeat(np.repeat(layout, block, axis=1), block, axis=2)


def dense_masked_attention(q, k, v, token_mask, causal, sm_scale=None):
    """Reference/fallback path: dense attention with the block mask
    applied elementwise. [B, S, H, D] layout; token_mask is [H, S, S]
    (shared across batch) or [B, H, S, S] (e.g. with key padding)."""
    b, s, h, d = q.shape
    scale = sm_scale or 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    mask = jnp.asarray(token_mask)
    if mask.ndim == 3:
        mask = mask[None]  # [1, H, S, S]
    if causal:
        mask = jnp.logical_and(mask, jnp.tril(jnp.ones((s, s), bool)))
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    # Fully-masked rows produce uniform probs over -1e30 → NaN-free zeros.
    probs = jnp.where(mask.any(axis=-1, keepdims=True), probs, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


class SparseSelfAttention:
    """Layout-cached sparse attention, one instance per layer.

    `forward(q, k, v)` takes [B, S, H, D] (the reference takes
    [B, H, S, D]; use `transpose_inputs=True` for that layout).
    """

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048,
                 transpose_inputs=False):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        if not isinstance(self.sparsity_config, SparsityConfig):
            raise TypeError("sparsity_config must be a SparsityConfig")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.transpose_inputs = transpose_inputs
        self._cache = {}

    @property
    def block(self):
        return self.sparsity_config.block

    def get_layout(self, seq_len):
        if seq_len not in self._cache:
            layout = self.sparsity_config.make_layout(seq_len)
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
            kernel = None
            block = self.block
            if seq_len % 128 == 0 and block % 128 == 0:
                # Kernel path uses 128-sized blocks; coarser layouts are
                # refined to 128 granularity.
                refine = block // 128
                fine = np.repeat(np.repeat(layout, refine, axis=1),
                                 refine, axis=2)
                kernel = BlockSparseAttention(fine, block=128,
                                              causal=causal)
            self._cache[seq_len] = (layout, kernel, causal)
        return self._cache[seq_len]

    def forward(self, query, key, value, rpe=None, key_padding_mask=None,
                attn_mask=None):
        if self.transpose_inputs:
            query, key, value = (x.transpose(0, 2, 1, 3)
                                 for x in (query, key, value))
        b, s, h, d = query.shape
        if s % self.block != 0:
            raise ValueError(
                f"sequence length {s} must be divisible by block "
                f"{self.block}")
        layout, kernel, causal = self.get_layout(s)

        use_kernel = (kernel is not None and d in (64, 128, 256)
                      and rpe is None and key_padding_mask is None
                      and attn_mask is None)
        if use_kernel:
            out = kernel(query, key, value)
        else:
            token_mask = layout_to_token_mask(layout, self.block)
            if key_padding_mask is not None:
                kpm = jnp.asarray(key_padding_mask, bool)  # [B, S], True=keep
                token_mask = jnp.logical_and(token_mask[None],
                                             kpm[:, None, None, :])
            out = dense_masked_attention(query, key, value, token_mask,
                                         causal)
        if self.transpose_inputs:
            out = out.transpose(0, 2, 1, 3)
        return out

    __call__ = forward
