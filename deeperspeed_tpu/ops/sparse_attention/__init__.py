from .bert_sparse_self_attention import BertSparseSelfAttention
from .matmul import MatMul, dense_to_sparse, sparse_to_dense
from .softmax import Softmax
from .sparse_attention_utils import SparseAttentionUtils
from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import (BigBirdSparsityConfig,
                              BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              LocalSlidingWindowSparsityConfig,
                              SparsityConfig, VariableSparsityConfig,
                              sparsity_config_from_dict)
