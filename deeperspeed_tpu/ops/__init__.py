from . import adam, lamb, op_builder, pallas, sparse_attention, transformer
from .transformer import (DeepSpeedTransformerConfig,
                          DeepSpeedTransformerLayer)
from .sparse_attention import SparseSelfAttention


def dispatch_report():
    """Last-dispatched kernel configuration, as one dict — the PUBLIC
    accessor over the kernels' internal dispatch records
    (`flash_attention._LAST_BLOCKS`, `decode_attention._LAST_BACKEND`).
    The bench `extra` columns, the telemetry capture exports, and the
    fleet trace metadata all consume this; WHICH block geometry / grid
    variant / decode backend produced a number is as load-bearing as
    the number itself.

    Keys (present once the corresponding kernel has dispatched):
    ``flash``: {"fwd": (bq, bk), "fwd_variant", "dkv", "dq",
    "bwd_variant"}; ``decode_attention``: {"decode": backend,
    "decode_kv": pool dtype — "int8" when the paged pools are
    quantized}; ``quant_matmul``: {"quant_matmul": backend} for the
    int8 weight-only matmul.
    """
    from .pallas.decode_attention import _LAST_BACKEND
    from .pallas.flash_attention import _LAST_BLOCKS
    from .pallas.quant_matmul import _LAST_BACKEND as _QMM_BACKEND
    return {"flash": dict(_LAST_BLOCKS),
            "decode_attention": dict(_LAST_BACKEND),
            "quant_matmul": dict(_QMM_BACKEND)}


__all__ = ["adam", "lamb", "op_builder", "pallas", "sparse_attention",
           "transformer", "DeepSpeedTransformerConfig",
           "DeepSpeedTransformerLayer", "SparseSelfAttention",
           "dispatch_report"]
