from . import adam, lamb, op_builder, pallas, sparse_attention, transformer
from .transformer import (DeepSpeedTransformerConfig,
                          DeepSpeedTransformerLayer)
from .sparse_attention import SparseSelfAttention

__all__ = ["adam", "lamb", "op_builder", "pallas", "sparse_attention",
           "transformer", "DeepSpeedTransformerConfig",
           "DeepSpeedTransformerLayer", "SparseSelfAttention"]
