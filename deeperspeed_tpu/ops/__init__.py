from . import adam, lamb
