"""Op availability registry — the TPU analogue of `op_builder/`
(reference: `op_builder/builder.py:81`, per-op `is_compatible()`).

The reference JIT-compiles CUDA extensions at first use; every Pallas
kernel here is compiled by XLA on first call, so "availability" is a
capability probe (backend, shape constraints), not a build step. `ds_report`
prints this matrix (reference `env_report.py:23`).
"""


def _on_tpu():
    import jax
    try:
        return jax.default_backend() == "tpu" or \
            "TPU" in str(jax.devices()[0])
    except Exception:
        return False


def fused_adam_available():
    from .adam.fused_adam import FusedAdam  # noqa: F401
    return True


def cpu_adam_available():
    from .adam.fused_adam import DeepSpeedCPUAdam  # noqa: F401
    return True


def fused_lamb_available():
    from .lamb.fused_lamb import FusedLamb  # noqa: F401
    return True


def transformer_available():
    from .transformer import DeepSpeedTransformerLayer  # noqa: F401
    return True


def stochastic_transformer_available():
    # stochastic_mode is accepted by DeepSpeedTransformerConfig; bf16
    # compute supersedes the CUDA stochastic rounding mode.
    return transformer_available()


def flash_attention_available():
    from .pallas.flash_attention import flash_attention  # noqa: F401
    return True


def quant_matmul_available():
    # int8 weight-only matmul (per-channel scales, dequant-in-kernel)
    # for the serving decode/prefill weight path + the delayed-scaling
    # fp8/int8 training matmuls (docs/quantization.md)
    from .pallas.quant_matmul import quant_matmul  # noqa: F401
    return True


def int8_kv_decode_available():
    # the dequant-at-DMA int8 decode-attention variant. Probing the
    # KERNEL module only — importing inference.kv_cache would execute
    # the whole serving package __init__, and an unrelated serving-stack
    # import failure would misreport THIS op as unavailable
    from .pallas.decode_attention import (  # noqa: F401
        _decode_kernel_quant, paged_decode_attention)
    return True


def sparse_attn_available():
    from .sparse_attention import SparseSelfAttention  # noqa: F401
    return True


def async_io_available():
    from ..runtime.swap_tensor.aio_engine import AsyncIOEngine
    return AsyncIOEngine.available()


def utils_available():
    # flatten/unflatten is native jnp (ravel/concatenate); always present.
    return True


def _builder_checks():
    """One registry: the op_builder builders are the source of truth
    (`ds_report` renders this dict); flash_attention is a kernel-level
    probe with no reference builder, so it is appended here."""
    from .op_builder import ALL_OPS as BUILDERS
    checks = {name: builder.is_compatible
              for name, builder in BUILDERS.items()}
    # keep flash_attention between the transformer and sparse_attn rows;
    # the quant kernel backends follow it (docs/quantization.md)
    ordered = {}
    for name in checks:
        ordered[name] = checks[name]
        if name == "stochastic_transformer":
            ordered["flash_attention"] = flash_attention_available
            ordered["quant_matmul"] = quant_matmul_available
            ordered["int8_kv_decode"] = int8_kv_decode_available
    return ordered


ALL_OPS = _builder_checks()
