from .fused_lamb import FusedLamb
