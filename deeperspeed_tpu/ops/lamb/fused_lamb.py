"""LAMB optimizer (reference: `deepspeed/ops/lamb/fused_lamb.py:12`,
`csrc/lamb/fused_lamb_cuda_kernel.cu`).

LAMB = Adam with a per-tensor "trust ratio" ||p|| / ||update|| scaling the
step (You et al. 2019). The reference computes the two norms in-kernel; XLA
fuses the reductions here. Norm clamps (`max_coeff`/`min_coeff`) match the
reference wrapper's options.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object
    exp_avg_sq: object


class FusedLamb:
    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, max_coeff=10.0,
                 min_coeff=0.01, amsgrad=False):
        if amsgrad:
            raise ValueError("FusedLamb does not support amsgrad")
        self.param_groups = [{
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
            "bias_correction": bias_correction,
            "max_coeff": max_coeff,
            "min_coeff": min_coeff,
        }]
        self.eps_inside_sqrt = eps_inside_sqrt
        self.defaults = dict(self.param_groups[0])
        # Populated per-step for parity with the wrapper's introspection
        # hooks (1-bit LAMB reads these).
        self.lamb_coeffs = []

    def init_state(self, master_params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        return LambState(
            step=jnp.asarray(0, jnp.int32),
            exp_avg=jax.tree_util.tree_map(zeros, master_params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, master_params),
        )

    def update(self, grads, state, master_params, lr=None):
        group = self.param_groups[0]
        beta1, beta2 = group["betas"]
        eps = group["eps"]
        weight_decay = group["weight_decay"]
        max_coeff = group["max_coeff"]
        min_coeff = group["min_coeff"]
        lr = group["lr"] if lr is None else lr
        step = state.step + 1

        if group["bias_correction"]:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        lamb_coeffs = []

        def leaf_update(p, g, m, v):
            g = g.astype(jnp.float32)
            p = p.astype(jnp.float32)
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * jnp.square(g)
            if self.eps_inside_sqrt:
                denom = jnp.sqrt(v_new / bc2 + eps)
            else:
                denom = jnp.sqrt(v_new / bc2) + eps
            update = (m_new / bc1) / denom
            if weight_decay != 0.0:
                update = update + weight_decay * p
            p_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(update.reshape(-1))
            trust = jnp.where(
                (p_norm > 0) & (u_norm > 0),
                jnp.clip(p_norm / u_norm, min_coeff, max_coeff), 1.0)
            lamb_coeffs.append(trust)
            return p - lr * trust * update, m_new, v_new

        flat_p, treedef = jax.tree_util.tree_flatten(master_params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)

        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pn, mn, vn = leaf_update(p, g, m, v)
            new_p.append(pn)
            new_m.append(mn)
            new_v.append(vn)
        self.lamb_coeffs = lamb_coeffs

        return (jax.tree_util.tree_unflatten(treedef, new_p),
                LambState(step=step,
                          exp_avg=jax.tree_util.tree_unflatten(treedef, new_m),
                          exp_avg_sq=jax.tree_util.tree_unflatten(
                              treedef, new_v)))

    def get_lamb_coeffs(self):
        return self.lamb_coeffs

    def state_dict(self, state):
        return {
            "step": int(state.step),
            "exp_avg": state.exp_avg,
            "exp_avg_sq": state.exp_avg_sq,
            "param_groups": [dict(g) for g in self.param_groups],
        }

    def load_state_dict(self, sd):
        self.param_groups = [dict(g) for g in sd["param_groups"]]
        return LambState(step=jnp.asarray(sd["step"], jnp.int32),
                         exp_avg=sd["exp_avg"],
                         exp_avg_sq=sd["exp_avg_sq"])
