from .fused_adam import DeepSpeedCPUAdam, FusedAdam
