"""Adam/AdamW optimizer (reference: `deepspeed/ops/adam/fused_adam.py:15`,
`csrc/adam/multi_tensor_adam.cu`).

The reference fuses Adam across chunked tensor lists in one CUDA kernel; on
TPU the update below is a handful of elementwise ops per leaf that XLA fuses
into a single kernel over each (sharded) parameter — the multi-tensor-apply
machinery is unnecessary. A Pallas flat-shard variant lives in
`deeperspeed_tpu.ops.pallas.optimizer` for the offload tier.

API shape follows torch optimizers: hyperparameters live in
``param_groups[0]`` so DeepSpeed LR schedules can mutate ``lr``/``betas``;
the math is pure (`init_state` / `update`) so the engine can jit it with
ZeRO shardings on the state.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    step: jnp.ndarray     # i32 scalar
    exp_avg: object       # pytree like params (fp32)
    exp_avg_sq: object    # pytree like params (fp32)


class FusedAdam:
    """Adam / AdamW ("adam_w_mode") with optional bias correction."""

    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True,
                 state_dtype="float32"):
        if amsgrad:
            raise ValueError("FusedAdam does not support amsgrad "
                             "(reference parity: fused_adam.py:47)")
        self.adam_w_mode = adam_w_mode
        # TPU-native extension beyond the reference: moments may REST in
        # bfloat16 (math still runs fp32 per step). Halves optimizer
        # bytes — with fp16_master_weights_and_grads it brings a 1.5B
        # model's full training state inside a 16 GB chip.
        self.state_dtype = jax.dtypes.canonicalize_dtype(state_dtype)
        self.param_groups = [{
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
            "bias_correction": bias_correction,
        }]
        self.defaults = dict(self.param_groups[0])

    # -- pure functional core (jit-safe) ----------------------------------

    def init_state(self, master_params):
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, self.state_dtype), master_params)
        return AdamState(
            step=jnp.asarray(0, jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree_util.tree_map(jnp.copy, zeros),
        )

    def update(self, grads, state, master_params, lr=None):
        """One optimizer step on fp32 master params. Returns
        (new_master_params, new_state). All inputs may be ZeRO-sharded; the
        math is elementwise so sharding propagates untouched."""
        group = self.param_groups[0]
        beta1, beta2 = group["betas"]
        eps = group["eps"]
        weight_decay = group["weight_decay"]
        lr = group["lr"] if lr is None else lr
        step = state.step + 1

        if group["bias_correction"]:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def leaf_update(p, g, m, v):
            g = g.astype(jnp.float32)
            p = p.astype(jnp.float32)
            store = m.dtype   # moments rest in state_dtype, math in fp32
            m = m.astype(jnp.float32)
            v = v.astype(jnp.float32)
            if weight_decay != 0.0 and not self.adam_w_mode:
                g = g + weight_decay * p  # classic L2
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * jnp.square(g)
            update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay != 0.0 and self.adam_w_mode:
                update = update + weight_decay * p  # decoupled decay
            return (p - lr * update, m_new.astype(store),
                    v_new.astype(store))

        flat_p, treedef = jax.tree_util.tree_flatten(master_params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)

        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pn, mn, vn = leaf_update(p, g, m, v)
            new_p.append(pn)
            new_m.append(mn)
            new_v.append(vn)

        return (jax.tree_util.tree_unflatten(treedef, new_p),
                AdamState(step=step,
                          exp_avg=jax.tree_util.tree_unflatten(treedef, new_m),
                          exp_avg_sq=jax.tree_util.tree_unflatten(
                              treedef, new_v)))

    # -- state (de)serialization ------------------------------------------

    def state_dict(self, state):
        return {
            "step": int(state.step),
            "exp_avg": state.exp_avg,
            "exp_avg_sq": state.exp_avg_sq,
            "param_groups": [dict(g) for g in self.param_groups],
        }

    def load_state_dict(self, sd):
        self.param_groups = [dict(g) for g in sd["param_groups"]]
        return AdamState(step=jnp.asarray(sd["step"], jnp.int32),
                         exp_avg=sd["exp_avg"],
                         exp_avg_sq=sd["exp_avg_sq"])


class DeepSpeedCPUAdam(FusedAdam):
    """API-compat alias for the ZeRO-Offload host optimizer (reference:
    `csrc/adam/cpu_adam.cpp`). The actual host-resident stepping lives in
    the C++ offload tier (csrc/); when offload is disabled this behaves as
    FusedAdam on device."""

    def __init__(self, params=None, **kwargs):
        kwargs.setdefault("adam_w_mode", True)
        super().__init__(params, **kwargs)
