"""ctypes binding + host optimizer around the C++ CPU Adam
(`csrc/adam/cpu_adam.cpp`; reference wrapper:
`deepspeed/ops/adam/cpu_adam.py`).

Used by the ZeRO-Offload tier: fp32 masters + moments live in host DRAM as
numpy arrays; each step runs the fused C++ kernel per flat shard and emits
a bf16 shadow for upload, so the device only ever holds compute-dtype
params.
"""

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

from ...utils.logging import logger

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "..", "csrc",
                     "adam", "cpu_adam.cpp")
_SO_PATH = os.path.join(tempfile.gettempdir(),
                        "deeperspeed_tpu_cpu_adam.so")
_lib = None
_lock = threading.Lock()


def _build_library():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        src = os.path.abspath(_CSRC)
        if not os.path.isfile(_SO_PATH) or \
                os.path.getmtime(_SO_PATH) < os.path.getmtime(src):
            cmd = ["g++", "-O3", "-march=native", "-funroll-loops",
                   "-shared", "-fPIC", "-std=c++17", "-pthread", src,
                   "-o", _SO_PATH]
            logger.info(f"building cpu adam: {' '.join(cmd)}")
            subprocess.check_call(cmd)
        lib = ctypes.CDLL(_SO_PATH)
        lib.ds_cpu_adam_step.restype = None
        lib.ds_cpu_adam_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
        ]
        _lib = lib
        return lib


def cpu_adam_available():
    try:
        _build_library()
        return True
    except Exception:
        return False


def _ptr(arr):
    return arr.ctypes.data_as(ctypes.c_void_p)


class NativeCPUAdam:
    """Host-resident Adam over flat numpy shards.

    state: dict with 'step' (int), and per-leaf flat fp32 arrays 'master',
    'exp_avg', 'exp_avg_sq' stored in self — the caller owns only grads.
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True, adam_w_mode=True,
                 num_threads=0):
        self._lib = _build_library()
        self.param_groups = [{
            "lr": lr, "betas": tuple(betas), "eps": eps,
            "weight_decay": weight_decay,
            "bias_correction": bias_correction,
        }]
        self.adam_w_mode = adam_w_mode
        self.num_threads = num_threads
        self.step_count = 0

    def step_flat(self, master, grads, exp_avg, exp_avg_sq, lr=None,
                  bf16_out=None, step=None):
        """One in-place Adam step on a flat fp32 shard. `step` is the
        1-based optimizer step for bias correction; when None the internal
        counter advances (callers stepping multiple shards of the same
        optimizer step must pass it explicitly)."""
        g = self.param_groups[0]
        if step is None:
            self.step_count += 1
            step = self.step_count
        else:
            self.step_count = max(self.step_count, step)
        lr = float(g["lr"] if lr is None else lr)
        master = np.ascontiguousarray(master, np.float32)
        grads = np.ascontiguousarray(grads, np.float32)
        assert master.shape == grads.shape == exp_avg.shape == \
            exp_avg_sq.shape
        bf16_ptr = _ptr(bf16_out) if bf16_out is not None else None
        self._lib.ds_cpu_adam_step(
            _ptr(master), _ptr(grads), _ptr(exp_avg), _ptr(exp_avg_sq),
            master.size, step, lr, g["betas"][0], g["betas"][1],
            g["eps"], g["weight_decay"], int(self.adam_w_mode),
            int(g["bias_correction"]), bf16_ptr, self.num_threads)
        return master
