# Re-export for parity with `deepspeed.pipe` (reference deepspeed/pipe/).
from ..runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec
