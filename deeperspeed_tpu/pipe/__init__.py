# Re-export for parity with `deepspeed.pipe` (reference deepspeed/pipe/).
from ..runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec

# The compiled 1F1B runtime pieces (config-driven via the "pipeline"
# JSON block; see docs/parallelism.md): the shard_map executor, the
# flagship-model wrapper, and the schedule's bubble arithmetic.
from ..parallel.pipeline_spmd import (GPTNeoXPipeSPMD,  # noqa: F401
                                      module_pipeline_loss_fn,
                                      pipeline_loss_fn)
from ..parallel.schedule import bubble_fraction  # noqa: F401
