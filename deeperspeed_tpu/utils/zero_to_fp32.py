#!/usr/bin/env python
"""Offline ZeRO-checkpoint → consolidated fp32 state-dict conversion.

Capability parity with the reference's recovery script
(`deepspeed/utils/zero_to_fp32.py`, copied into every checkpoint directory
by `engine.save_checkpoint`, reference `engine.py:1800-1808`): a user can,
at any later time and **without the framework installed**, turn a sharded
ZeRO checkpoint into a single framework-free fp32 state dict.

Layout consumed (written by `deeperspeed_tpu.checkpoint.checkpointing`):

    {ckpt_dir}/mp_rank_{mp:02d}_model_states.pt        # params + counters
    {ckpt_dir}/zero_pp_rank_{dp}_mp_rank_{mp:02d}_optim_states.pt

The zero files carry per-dp-rank slices of the fp32 masters plus a
``fp32_master_dims`` map saying which dim each leaf was sliced along
(GSPMD convention: ceil-chunked, last shard may be short), so the merge is
a plain concatenate — no flat-buffer offset math like the torch original
needed.

Usage::

    python zero_to_fp32.py <checkpoint_dir> <output_file>

Output is a ``{param_path: np.float32 ndarray}`` dict saved with torch
(falls back to pickle), loadable anywhere.
"""

import os
import sys

if __package__ in (None, ""):
    # Run as a standalone script: python puts THIS directory first on
    # sys.path, where sibling modules (logging.py, timer.py) shadow the
    # stdlib and break third-party imports (torch's `import logging`
    # resolves to ours). The script is self-contained — drop the dir.
    _here = os.path.dirname(os.path.abspath(__file__))
    sys.path[:] = [p for p in sys.path
                   if os.path.abspath(p or os.getcwd()) != _here]

import argparse
import glob
import pickle
import re

import numpy as np

try:
    import torch
    _HAVE_TORCH = True
except Exception:  # pragma: no cover
    _HAVE_TORCH = False


def _load(path):
    if _HAVE_TORCH:
        return torch.load(path, map_location="cpu", weights_only=False)
    with open(path, "rb") as f:  # pragma: no cover
        return pickle.load(f)


def _save(obj, path):
    if _HAVE_TORCH:
        torch.save(obj, path)
    else:  # pragma: no cover
        with open(path, "wb") as f:
            pickle.dump(obj, f)


def get_model_state_file(checkpoint_dir, mp_rank=0):
    path = os.path.join(checkpoint_dir,
                        f"mp_rank_{mp_rank:02d}_model_states.pt")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"can't find {path}")
    return path


def get_zero_files(checkpoint_dir, mp_rank=0):
    """Zero shard files ordered by dp rank (numeric, not lexicographic)."""
    pattern = os.path.join(
        checkpoint_dir, f"zero_pp_rank_*_mp_rank_{mp_rank:02d}_optim_states.pt")
    files = glob.glob(pattern)

    def dp_rank(path):
        m = re.search(r"zero_pp_rank_(\d+)_", os.path.basename(path))
        return int(m.group(1)) if m else 0

    return sorted(files, key=dp_rank)


def _merge_sliced(per_rank, dims, saved_dp, flat_shapes=None):
    """Merge per-dp-rank {path: slice} dicts into full arrays. A dim of
    "flat" marks a ragged leaf saved as rank slices of its raveled
    natural array; `flat_shapes[key]` restores the natural shape."""
    merged = {}
    for key in per_rank[0]:
        dim = dims.get(key) if dims else None
        if dim is None or saved_dp == 1:
            merged[key] = np.asarray(per_rank[0][key])
        elif dim == "flat":
            flat = np.concatenate(
                [np.asarray(r[key]).ravel() for r in per_rank])
            merged[key] = flat.reshape((flat_shapes or {})[key])
        else:
            merged[key] = np.concatenate(
                [np.asarray(r[key]) for r in per_rank], axis=dim)
    return merged


def _decode_raw(buf_u8, dtype_str):
    """Raw little-endian bytes → fp32, framework-free: bfloat16 (the
    usual compute dtype) is decoded by bit-shifting into fp32 — no
    ml_dtypes/jax needed, keeping the script's runs-anywhere contract."""
    if dtype_str == "bfloat16":
        u16 = np.frombuffer(buf_u8, np.uint16)
        return (u16.astype(np.uint32) << 16).view(np.float32)
    return np.frombuffer(buf_u8, np.dtype(dtype_str)).astype(np.float32)


def _streamed_nvme_state_dict(checkpoint_dir, meta):
    """Consolidate a streamed-NVMe checkpoint (written by
    `_save_streamed_nvme_checkpoint`: raw `param_seg_*.swp` /
    `opt_{gid}_*.swp` files + a param manifest in the model-states meta)
    into {path: fp32 ndarray} with O(one leaf / one segment) memory —
    the export path for beyond-DRAM models.
    """
    man = meta.get("param_manifest")
    if man is None:
        raise RuntimeError(
            "streamed-NVMe checkpoint has no param_manifest (saved by a "
            "pre-round-4 framework version); re-save the checkpoint to "
            "make it offline-convertible")
    paths = man["leaf_paths"]
    shapes = [tuple(s) for s in man["leaf_shapes"]]
    out = {}

    # 1) exact fp32 masters, DRAM tier: stored inline in the meta
    host_state = (meta.get("optimizer") or {}).get("host_state")
    if host_state is not None:
        for gid, (path, shape) in enumerate(zip(paths, shapes)):
            out[path] = np.asarray(
                host_state["master"][gid], np.float32).reshape(shape)
        return out

    # 2) exact fp32 masters, NVMe tier: one raw flat file per leaf.
    # PARTIAL master sets mean a truncated/corrupted checkpoint — error
    # with the missing file rather than silently downgrading precision.
    have = [os.path.isfile(
        os.path.join(checkpoint_dir, f"opt_{gid}_master.swp"))
        for gid in range(len(paths))]
    if all(have):
        for gid, (path, shape) in enumerate(zip(paths, shapes)):
            f = os.path.join(checkpoint_dir, f"opt_{gid}_master.swp")
            out[path] = np.fromfile(f, np.float32).reshape(shape)
        return out
    if any(have):
        missing = [f"opt_{g}_master.swp" for g, h in enumerate(have)
                   if not h]
        raise RuntimeError(
            f"incomplete streamed checkpoint: {len(missing)} fp32 master "
            f"file(s) missing (e.g. {missing[:3]}); refusing to silently "
            f"downgrade to the lossy compute-dtype param upcast. If the "
            f"masters are truly gone, delete ALL opt_*_master.swp files "
            f"to opt in to the param-segment fallback")

    # 3) fallback: upcast the compute-dtype param segments themselves
    for seg, rows in man["segment_layout"].items():
        f = os.path.join(checkpoint_dir, f"param_seg_{seg}.swp")
        with open(f, "rb") as fh:
            raw = fh.read()
        off = 0
        for gid, shape, dtype_str in rows:
            itemsize = 2 if dtype_str == "bfloat16" else \
                np.dtype(dtype_str).itemsize
            n = int(np.prod(shape)) if shape else 1
            nbytes = n * itemsize
            if paths[gid] not in out:  # tied leaves: first segment wins
                out[paths[gid]] = _decode_raw(
                    raw[off:off + nbytes], dtype_str).reshape(shape)
            off += nbytes
    missing = [p for p in paths if p not in out]
    if missing:
        raise RuntimeError(
            f"streamed checkpoint covers {len(out)}/{len(paths)} "
            f"parameters; missing e.g. {missing[:3]}")
    return out


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, mp_rank=0):
    """Return {param_path: fp32 ndarray} for the checkpoint.

    Prefers the fp32 masters from the zero shards (exact optimizer view);
    falls back to upcasting the bf16/fp16 module weights when the
    checkpoint carries no masters (fp32 training without ZeRO).
    Streamed-NVMe checkpoints (ZeRO-Infinity beyond-DRAM tier) are
    consolidated from their raw segment/master files via the manifest.
    """
    # streamed-NVMe checkpoints are recognizable by their raw segment
    # files — only then is the (potentially huge) model-states file
    # loaded early to read the manifest
    if glob.glob(os.path.join(checkpoint_dir, "param_seg_*.swp")):
        meta = _load(get_model_state_file(checkpoint_dir, mp_rank))
        if isinstance(meta, dict) and meta.get("streamed_nvme"):
            return _streamed_nvme_state_dict(checkpoint_dir, meta)

    zero_files = get_zero_files(checkpoint_dir, mp_rank)
    if zero_files:
        shards = [_load(f) for f in zero_files]
        saved_dp = shards[0].get("partition_count", len(shards))
        if saved_dp != len(shards):
            raise RuntimeError(
                f"incomplete checkpoint: found {len(shards)} zero shard "
                f"files but the checkpoint was saved with "
                f"partition_count={saved_dp}")
        if shards[0].get("fp32_master") is not None:
            masters = [s["fp32_master"] for s in shards]
            dims = shards[0].get("fp32_master_dims", {}) or {}
            merged = _merge_sliced(
                masters, dims, saved_dp,
                shards[0].get("fp32_master_flat_shapes"))
            return {k: np.asarray(v, np.float32) for k, v in merged.items()}
        osd = shards[0].get("optimizer_state_dict", {})
        if osd.get("host_offload"):
            # ZeRO-Offload: flat host-resident masters + path/shape tables.
            paths = osd.get("param_paths")
            shapes = osd.get("param_shapes")
            if paths is None or shapes is None:
                raise RuntimeError(
                    "host-offload checkpoint lacks param_paths/param_shapes "
                    "tables; exact fp32 masters cannot be mapped to "
                    "parameter names offline (re-save with a newer "
                    "framework version)")
            return {path: np.asarray(m, np.float32).reshape(shape)
                    for path, m, shape in zip(paths, osd["master"], shapes)}

    model_state = _load(get_model_state_file(checkpoint_dir, mp_rank))
    arrays = model_state["module"]["arrays"]
    return {k: np.asarray(v, np.float32) for k, v in arrays.items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               mp_rank=0):
    state_dict = get_fp32_state_dict_from_zero_checkpoint(
        checkpoint_dir, mp_rank)
    print(f"Saving fp32 state dict ({len(state_dict)} tensors, "
          f"{sum(v.size for v in state_dict.values()):,} elements) "
          f"to {output_file}")
    _save(state_dict, output_file)
    return state_dict


# Reference-spelled alias (`utils/zero_to_fp32.py:70` names it
# convert_zero_chkpt_to_fp32_consolid_state_dict).
convert_zero_chkpt_to_fp32_consolid_state_dict = \
    convert_zero_checkpoint_to_fp32_state_dict


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Extract a consolidated fp32 state dict from a "
                    "DeeperSpeed-TPU ZeRO checkpoint directory")
    parser.add_argument("checkpoint_dir",
                        help="checkpoint directory, e.g. global_step100")
    parser.add_argument("output_file",
                        help="where to save the consolidated fp32 state "
                             "dict, e.g. model_fp32.bin")
    parser.add_argument("--mp_rank", type=int, default=0,
                        help="model-parallel rank to extract (default 0)")
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, args.mp_rank)


if __name__ == "__main__":
    main()
