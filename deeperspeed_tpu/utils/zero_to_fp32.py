#!/usr/bin/env python
"""Offline ZeRO-checkpoint → consolidated fp32 state-dict conversion.

Capability parity with the reference's recovery script
(`deepspeed/utils/zero_to_fp32.py`, copied into every checkpoint directory
by `engine.save_checkpoint`, reference `engine.py:1800-1808`): a user can,
at any later time and **without the framework installed**, turn a sharded
ZeRO checkpoint into a single framework-free fp32 state dict.

Layout consumed (written by `deeperspeed_tpu.checkpoint.checkpointing`):

    {ckpt_dir}/mp_rank_{mp:02d}_model_states.pt        # params + counters
    {ckpt_dir}/zero_pp_rank_{dp}_mp_rank_{mp:02d}_optim_states.pt

The zero files carry per-dp-rank slices of the fp32 masters plus a
``fp32_master_dims`` map saying which dim each leaf was sliced along
(GSPMD convention: ceil-chunked, last shard may be short), so the merge is
a plain concatenate — no flat-buffer offset math like the torch original
needed.

Usage::

    python zero_to_fp32.py <checkpoint_dir> <output_file>

Output is a ``{param_path: np.float32 ndarray}`` dict saved with torch
(falls back to pickle), loadable anywhere.
"""

import argparse
import glob
import os
import pickle
import re

import numpy as np

try:
    import torch
    _HAVE_TORCH = True
except Exception:  # pragma: no cover
    _HAVE_TORCH = False


def _load(path):
    if _HAVE_TORCH:
        return torch.load(path, map_location="cpu", weights_only=False)
    with open(path, "rb") as f:  # pragma: no cover
        return pickle.load(f)


def _save(obj, path):
    if _HAVE_TORCH:
        torch.save(obj, path)
    else:  # pragma: no cover
        with open(path, "wb") as f:
            pickle.dump(obj, f)


def get_model_state_file(checkpoint_dir, mp_rank=0):
    path = os.path.join(checkpoint_dir,
                        f"mp_rank_{mp_rank:02d}_model_states.pt")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"can't find {path}")
    return path


def get_zero_files(checkpoint_dir, mp_rank=0):
    """Zero shard files ordered by dp rank (numeric, not lexicographic)."""
    pattern = os.path.join(
        checkpoint_dir, f"zero_pp_rank_*_mp_rank_{mp_rank:02d}_optim_states.pt")
    files = glob.glob(pattern)

    def dp_rank(path):
        m = re.search(r"zero_pp_rank_(\d+)_", os.path.basename(path))
        return int(m.group(1)) if m else 0

    return sorted(files, key=dp_rank)


def _merge_sliced(per_rank, dims, saved_dp, flat_shapes=None):
    """Merge per-dp-rank {path: slice} dicts into full arrays. A dim of
    "flat" marks a ragged leaf saved as rank slices of its raveled
    natural array; `flat_shapes[key]` restores the natural shape."""
    merged = {}
    for key in per_rank[0]:
        dim = dims.get(key) if dims else None
        if dim is None or saved_dp == 1:
            merged[key] = np.asarray(per_rank[0][key])
        elif dim == "flat":
            flat = np.concatenate(
                [np.asarray(r[key]).ravel() for r in per_rank])
            merged[key] = flat.reshape((flat_shapes or {})[key])
        else:
            merged[key] = np.concatenate(
                [np.asarray(r[key]) for r in per_rank], axis=dim)
    return merged


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, mp_rank=0):
    """Return {param_path: fp32 ndarray} for the checkpoint.

    Prefers the fp32 masters from the zero shards (exact optimizer view);
    falls back to upcasting the bf16/fp16 module weights when the
    checkpoint carries no masters (fp32 training without ZeRO).
    """
    zero_files = get_zero_files(checkpoint_dir, mp_rank)
    if zero_files:
        shards = [_load(f) for f in zero_files]
        saved_dp = shards[0].get("partition_count", len(shards))
        if saved_dp != len(shards):
            raise RuntimeError(
                f"incomplete checkpoint: found {len(shards)} zero shard "
                f"files but the checkpoint was saved with "
                f"partition_count={saved_dp}")
        if shards[0].get("fp32_master") is not None:
            masters = [s["fp32_master"] for s in shards]
            dims = shards[0].get("fp32_master_dims", {}) or {}
            merged = _merge_sliced(
                masters, dims, saved_dp,
                shards[0].get("fp32_master_flat_shapes"))
            return {k: np.asarray(v, np.float32) for k, v in merged.items()}
        osd = shards[0].get("optimizer_state_dict", {})
        if osd.get("host_offload"):
            # ZeRO-Offload: flat host-resident masters + path/shape tables.
            paths = osd.get("param_paths")
            shapes = osd.get("param_shapes")
            if paths is None or shapes is None:
                raise RuntimeError(
                    "host-offload checkpoint lacks param_paths/param_shapes "
                    "tables; exact fp32 masters cannot be mapped to "
                    "parameter names offline (re-save with a newer "
                    "framework version)")
            return {path: np.asarray(m, np.float32).reshape(shape)
                    for path, m, shape in zip(paths, osd["master"], shapes)}

    model_state = _load(get_model_state_file(checkpoint_dir, mp_rank))
    arrays = model_state["module"]["arrays"]
    return {k: np.asarray(v, np.float32) for k, v in arrays.items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               mp_rank=0):
    state_dict = get_fp32_state_dict_from_zero_checkpoint(
        checkpoint_dir, mp_rank)
    print(f"Saving fp32 state dict ({len(state_dict)} tensors, "
          f"{sum(v.size for v in state_dict.values()):,} elements) "
          f"to {output_file}")
    _save(state_dict, output_file)
    return state_dict


# Reference-spelled alias (`utils/zero_to_fp32.py:70` names it
# convert_zero_chkpt_to_fp32_consolid_state_dict).
convert_zero_chkpt_to_fp32_consolid_state_dict = \
    convert_zero_checkpoint_to_fp32_state_dict


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Extract a consolidated fp32 state dict from a "
                    "DeeperSpeed-TPU ZeRO checkpoint directory")
    parser.add_argument("checkpoint_dir",
                        help="checkpoint directory, e.g. global_step100")
    parser.add_argument("output_file",
                        help="where to save the consolidated fp32 state "
                             "dict, e.g. model_fp32.bin")
    parser.add_argument("--mp_rank", type=int, default=0,
                        help="model-parallel rank to extract (default 0)")
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, args.mp_rank)


if __name__ == "__main__":
    main()
