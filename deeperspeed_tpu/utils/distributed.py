"""Distributed init (reference: `deepspeed/utils/distributed.py:12`).

`torch.distributed.init_process_group` becomes
`jax.distributed.initialize`: one process per host, all chips addressed
through the mesh. Rendezvous from env vars (MASTER_ADDR/PORT, RANK,
WORLD_SIZE — same names the reference launcher exports) or MPI discovery
via mpi4py when requested.
"""

import os

import jax

from .logging import logger

_initialized = False


def init_distributed(dist_backend="xla", auto_mpi_discovery=True,
                     distributed_port=29500, verbose=True,
                     timeout=None, init_method=None):
    """Join the multi-host world if env/MPI rendezvous info is present;
    single-host runs are a no-op (all local chips already visible)."""
    global _initialized
    if _initialized:
        return

    _patch_azureml_env(verbose=verbose)

    required_env = ["RANK", "WORLD_SIZE", "MASTER_ADDR"]
    if auto_mpi_discovery and \
            not all(v in os.environ for v in required_env) and \
            "OMPI_COMM_WORLD_SIZE" in os.environ:
        mpi_discovery(distributed_port=distributed_port, verbose=verbose)

    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    if world_size <= 1:
        _initialized = True
        return

    rank = int(os.environ.get("RANK", "0"))
    addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
    port = os.environ.get("MASTER_PORT", str(distributed_port))
    if verbose:
        logger.info(
            f"Initializing jax.distributed: rank={rank}, "
            f"world_size={world_size}, coordinator={addr}:{port}")
    jax.distributed.initialize(
        coordinator_address=f"{addr}:{port}",
        num_processes=world_size,
        process_id=rank)
    _initialized = True


def _patch_azureml_env(verbose=True):
    """Map AzureML's OpenMPI env vars onto the standard rendezvous vars
    (reference `distributed.py`'s in_aml()/patch_aml_env path)."""
    if "AZUREML_EXPERIMENT_ID" not in os.environ:
        return
    if "OMPI_COMM_WORLD_RANK" not in os.environ:
        return
    os.environ.setdefault("RANK", os.environ["OMPI_COMM_WORLD_RANK"])
    os.environ.setdefault("WORLD_SIZE",
                          os.environ.get("OMPI_COMM_WORLD_SIZE", "1"))
    os.environ.setdefault("LOCAL_RANK",
                          os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", "0"))
    if int(os.environ["WORLD_SIZE"]) == 1:
        os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
    else:
        master = os.environ.get("AZ_BATCH_MASTER_NODE") or \
            os.environ.get("AZ_BATCHAI_MPI_MASTER_NODE")
        if not master:
            raise RuntimeError(
                "AzureML multi-node job but neither AZ_BATCH_MASTER_NODE "
                "nor AZ_BATCHAI_MPI_MASTER_NODE is set — cannot determine "
                "the rendezvous address (a localhost default would make "
                "every node rendezvous with itself)")
        addr, _, port = master.partition(":")
        os.environ.setdefault("MASTER_ADDR", addr)
        if port:
            os.environ.setdefault("MASTER_PORT", port)
    if verbose:
        logger.info("Detected AzureML environment; patched rendezvous "
                    "env vars from OMPI settings")


def mpi_discovery(distributed_port=29500, verbose=True):
    """Discover rank/world/master from MPI and export the standard env vars
    (reference `distributed.py:54`)."""
    from mpi4py import MPI

    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    world_size = comm.Get_size()

    import socket
    master_addr = None
    if rank == 0:
        master_addr = socket.gethostbyname(socket.gethostname())
    master_addr = comm.bcast(master_addr, root=0)

    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(distributed_port)
    os.environ["LOCAL_RANK"] = str(
        comm.Split_type(MPI.COMM_TYPE_SHARED).Get_rank())

    if verbose:
        logger.info(
            f"MPI discovery: rank={rank}, world_size={world_size}, "
            f"master_addr={master_addr}, master_port={distributed_port}")
