"""Distributed init (reference: `deepspeed/utils/distributed.py:12`).

`torch.distributed.init_process_group` becomes
`jax.distributed.initialize`: one process per host, all chips addressed
through the mesh. Rendezvous from env vars (MASTER_ADDR/PORT, RANK,
WORLD_SIZE — same names the reference launcher exports) or MPI discovery
via mpi4py when requested.
"""

import os
import time

import jax

from .logging import logger

_initialized = False

# Default deadline (seconds) for host-coordination barriers; None waits
# forever (the seed's behavior). Set via init_distributed(timeout=...) —
# a dead host then fails the BARRIER fast instead of hanging every
# surviving host until the scheduler gives up.
_collective_timeout = None
_barrier_serials = {}
_warned_no_client = False

# Coordination-service barriers are ALWAYS deadline-bearing when the
# client exists: with no timeout configured this default applies instead
# of degrading to the unbounded device-collective fallback. A dead peer
# then surfaces as a typed BarrierTimeoutError after this many seconds
# — still far faster (and infinitely more diagnosable) than an infinite
# sync_global_devices hang.
DEFAULT_BARRIER_TIMEOUT_S = 900.0

# fault-injection seam (runtime/fault_injection.py `barrier_timeout`
# faults): {tag_or_None: remaining_fires}. None matches any tag.
_forced_timeouts = {}


class BarrierTimeoutError(RuntimeError):
    """A host-coordination barrier blew its deadline: one or more peers
    never arrived (dead, preempted, or wedged). Carries the barrier tag
    and the elapsed wall time so the supervisor / logs can tell WHICH
    rendezvous failed and how long the survivors waited."""

    def __init__(self, tag, timeout_s, elapsed_s, cause=None):
        self.tag = tag
        self.timeout_s = float(timeout_s)
        self.elapsed_s = float(elapsed_s)
        super().__init__(
            f"barrier '{tag}' timed out after {elapsed_s:.1f}s "
            f"(deadline {timeout_s:.1f}s): a peer host never arrived"
            + (f" — {cause}" if cause else ""))


def inject_barrier_timeout(tag=None, times=1):
    """Arm the next `times` barrier call(s) (optionally only those with
    `tag`) to raise BarrierTimeoutError without waiting — the
    single-host test seam for the `barrier_timeout` fault kind."""
    _forced_timeouts[tag] = _forced_timeouts.get(tag, 0) + int(times)


def _pop_forced_timeout(tag):
    for key in (tag, None):
        if _forced_timeouts.get(key, 0) > 0:
            _forced_timeouts[key] -= 1
            if not _forced_timeouts[key]:
                del _forced_timeouts[key]
            return True
    return False


def get_collective_timeout():
    """The barrier/collective deadline configured via
    init_distributed(timeout=...), in seconds (None = wait forever)."""
    return _collective_timeout


def init_distributed(dist_backend="xla", auto_mpi_discovery=True,
                     distributed_port=29500, verbose=True,
                     timeout=None, init_method=None):
    """Join the multi-host world if env/MPI rendezvous info is present;
    single-host runs are a no-op (all local chips already visible).

    `timeout` (seconds) bounds BOTH the rendezvous
    (`jax.distributed.initialize(initialization_timeout=...)`) and every
    later `barrier()` call — a dead host fails fast instead of hanging
    the fleet forever."""
    global _initialized, _collective_timeout
    if timeout is not None:
        # recorded even on the early-return paths: barrier() must honor
        # the caller's deadline regardless of when the world formed
        _collective_timeout = float(timeout)
    if _initialized:
        return

    _patch_azureml_env(verbose=verbose)

    required_env = ["RANK", "WORLD_SIZE", "MASTER_ADDR"]
    if auto_mpi_discovery and \
            not all(v in os.environ for v in required_env) and \
            "OMPI_COMM_WORLD_SIZE" in os.environ:
        mpi_discovery(distributed_port=distributed_port, verbose=verbose)

    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    if world_size <= 1:
        _initialized = True
        return

    rank = int(os.environ.get("RANK", "0"))
    addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
    port = os.environ.get("MASTER_PORT", str(distributed_port))
    if verbose:
        logger.info(
            f"Initializing jax.distributed: rank={rank}, "
            f"world_size={world_size}, coordinator={addr}:{port}"
            + (f", timeout={timeout}s" if timeout is not None else ""))
    kwargs = {}
    if timeout is not None:
        kwargs["initialization_timeout"] = int(float(timeout))
    try:
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world_size,
            process_id=rank, **kwargs)
    except TypeError:
        # older jax without initialization_timeout: rendezvous is
        # unbounded, but barrier() deadlines below still apply
        if kwargs:
            logger.warning("this jax version does not accept "
                           "initialization_timeout; rendezvous will not "
                           "time out")
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world_size,
            process_id=rank)
    _initialized = True


def _distributed_client():
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # pragma: no cover - private-API drift
        return None


def barrier(tag, timeout=None):
    """Multihost host-level barrier with a fail-fast deadline.

    Whenever a coordination client exists the barrier runs on the
    coordination service (`wait_at_barrier`) under a deadline — the
    explicit `timeout` argument, the `init_distributed(timeout=...)`
    default, or `DEFAULT_BARRIER_TIMEOUT_S` as the floor — and a missing
    host raises a typed `BarrierTimeoutError` (tag + elapsed) instead of
    the raw gRPC DEADLINE_EXCEEDED: a preempted/dead peer costs seconds
    and is diagnosable, not an infinite hang inside a device collective.

    HAZARD: the `sync_global_devices` fallback (no client — single
    controller, or jax builds without the client API) is a DEVICE
    collective with NO deadline of any kind: a dead peer hangs every
    surviving host until the cluster scheduler reaps the job. It is kept
    only as a last resort; callers that need fail-fast semantics must
    run under `jax.distributed.initialize` (the launcher's default).
    Single-process: no-op (forced-timeout injection still fires, so the
    fault-injection harness can drive the failure path on one host)."""
    if jax.process_count() <= 1 and not _forced_timeouts:
        return
    timeout = _collective_timeout if timeout is None else timeout
    if _pop_forced_timeout(tag):
        raise BarrierTimeoutError(
            tag, timeout or DEFAULT_BARRIER_TIMEOUT_S, 0.0,
            cause="injected fault (barrier_timeout)")
    if jax.process_count() <= 1:
        return
    client = _distributed_client()
    if client is not None:
        # the client path is ALWAYS deadline-bearing: an unbounded
        # coordination wait would just reproduce the device-collective
        # hang with extra steps
        timeout = float(timeout) if timeout else DEFAULT_BARRIER_TIMEOUT_S
        # wait_at_barrier ids must be unique per rendezvous; every
        # host derives the same serial for the same call site order
        serial = _barrier_serials.get(tag, 0)
        _barrier_serials[tag] = serial + 1
        t0 = time.monotonic()
        try:
            client.wait_at_barrier(f"{tag}:{serial}",
                                   int(timeout * 1000))
        except Exception as e:
            elapsed = time.monotonic() - t0
            # DEADLINE_EXCEEDED from a missing peer; re-raise typed so
            # callers (checkpoint commit, supervisor handoff) can tell a
            # barrier timeout from a generic runtime error
            if "DEADLINE" in str(e).upper() or elapsed >= timeout * 0.9:
                raise BarrierTimeoutError(tag, timeout, elapsed,
                                          cause=e) from e
            raise
        return
    if timeout:
        global _warned_no_client
        if not _warned_no_client:  # pragma: no cover - env dependent
            _warned_no_client = True
            logger.warning("barrier timeout requested but no distributed "
                           "client is available; falling back to the "
                           "UNBOUNDED device-collective barrier (a dead "
                           "peer will hang this job until the scheduler "
                           "reaps it)")
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def _patch_azureml_env(verbose=True):
    """Map AzureML's OpenMPI env vars onto the standard rendezvous vars
    (reference `distributed.py`'s in_aml()/patch_aml_env path)."""
    if "AZUREML_EXPERIMENT_ID" not in os.environ:
        return
    if "OMPI_COMM_WORLD_RANK" not in os.environ:
        return
    os.environ.setdefault("RANK", os.environ["OMPI_COMM_WORLD_RANK"])
    os.environ.setdefault("WORLD_SIZE",
                          os.environ.get("OMPI_COMM_WORLD_SIZE", "1"))
    os.environ.setdefault("LOCAL_RANK",
                          os.environ.get("OMPI_COMM_WORLD_LOCAL_RANK", "0"))
    if int(os.environ["WORLD_SIZE"]) == 1:
        os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
    else:
        master = os.environ.get("AZ_BATCH_MASTER_NODE") or \
            os.environ.get("AZ_BATCHAI_MPI_MASTER_NODE")
        if not master:
            raise RuntimeError(
                "AzureML multi-node job but neither AZ_BATCH_MASTER_NODE "
                "nor AZ_BATCHAI_MPI_MASTER_NODE is set — cannot determine "
                "the rendezvous address (a localhost default would make "
                "every node rendezvous with itself)")
        addr, _, port = master.partition(":")
        os.environ.setdefault("MASTER_ADDR", addr)
        if port:
            os.environ.setdefault("MASTER_PORT", port)
    if verbose:
        logger.info("Detected AzureML environment; patched rendezvous "
                    "env vars from OMPI settings")


def mpi_discovery(distributed_port=29500, verbose=True):
    """Discover rank/world/master from MPI and export the standard env vars
    (reference `distributed.py:54`)."""
    from mpi4py import MPI

    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    world_size = comm.Get_size()

    import socket
    master_addr = None
    if rank == 0:
        master_addr = socket.gethostbyname(socket.gethostname())
    master_addr = comm.bcast(master_addr, root=0)

    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(distributed_port)
    os.environ["LOCAL_RANK"] = str(
        comm.Split_type(MPI.COMM_TYPE_SHARED).Get_rank())

    if verbose:
        logger.info(
            f"MPI discovery: rank={rank}, world_size={world_size}, "
            f"master_addr={master_addr}, master_port={distributed_port}")
