from .distributed import init_distributed, mpi_discovery
from .logging import log_dist, logger
from .timer import SynchronizedWallClockTimer, ThroughputTimer
