"""Timers (reference: `deepspeed/utils/timer.py`).

`SynchronizedWallClockTimer` fences XLA's async dispatch with
`jax.block_until_ready`/`jax.effects_barrier` where the reference used
`cuda.synchronize()`. `ThroughputTimer` reports samples/sec with warmup
skip.
"""

import contextlib
import time

import psutil

import jax

from .logging import logger


def _device_barrier():
    """Drain outstanding async device work so wall-clock is meaningful."""
    try:
        jax.effects_barrier()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named timer group with device-synchronized start/stop."""

    class Timer:
        # time.monotonic, not time.time: an NTP slew or wall-clock jump
        # mid-span corrupts elapsed (negative or hours-long "steps" have
        # been observed on preemptible fleets); monotonic can't go back.
        def __init__(self, name):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.monotonic()

        def start(self):
            assert not self.started_, f"{self.name_} timer already started"
            _device_barrier()
            self.start_time = time.monotonic()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, f"{self.name_} timer not started"
            _device_barrier()
            if reset:
                self.elapsed_ = time.monotonic() - self.start_time
            else:
                self.elapsed_ += time.monotonic() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop()
            elapsed = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            alloc = stats.get("bytes_in_use", 0) / 2 ** 30
            peak = stats.get("peak_bytes_in_use", 0) / 2 ** 30
            return f"hbm in-use: {alloc:.2f} GB, peak: {peak:.2f} GB"
        except Exception:
            return "hbm stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name not in self.timers:
                continue
            elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / \
                normalizer
            string += f" | {name}: {elapsed:.2f}"
        if memory_breakdown:
            string += f" | {self.memory_usage()}"
        logger.info(string)


class ThroughputTimer:
    """Samples/sec with configurable warmup skip (reference
    `timer.py:105`)."""

    def __init__(self, batch_size, num_workers=1, start_step=2,
                 steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _device_barrier()
            self.start_time = time.monotonic()

    def stop(self, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        self.global_step_count += 1
        if self.start_time > 0:
            _device_barrier()
            self.end_time = time.monotonic()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if report_speed and \
                    self.global_step_count % self.steps_per_output == 0:
                avg = self.avg_samples_per_sec()
                if avg > 0:   # still in warmup: nothing meaningful yet
                    self.logging(
                        f"{self.global_step_count}/"
                        f"{self.micro_step_count}, "
                        f"SamplesPerSec={avg:.2f}")
                if self.monitor_memory:
                    vm = psutil.virtual_memory()
                    self.logging(f"virtual memory used: "
                                 f"{vm.used / 2**30:.2f} GB, "
                                 f"percent: {vm.percent}%")

    def avg_samples_per_sec(self):
        # 0.0 (not -inf) before warmup completes: callers feed this into
        # logs and monitor scalars, and a -inf both reads as garbage and
        # poisons downstream aggregation.
        if self.global_step_count > self.start_step and \
                self.total_elapsed_time > 0:
            samples = self.batch_size * self.num_workers
            total_step_offset = self.global_step_count - self.start_step
            avg_time_per_step = self.total_elapsed_time / total_step_offset
            return samples / avg_time_per_step
        return 0.0


@contextlib.contextmanager
def profiler_trace(logdir, create_perfetto_trace=False):
    """XProf/TensorBoard trace of everything dispatched inside the block
    (the TPU-native face of the reference's `wall_clock_breakdown` CUDA
    timers, SURVEY §5.1): per-kernel device timelines, HLO cost
    attribution, host/device overlap.

    with profiler_trace("/tmp/trace"):
        engine.train_batch(batch=...)
    # then: tensorboard --logdir /tmp/trace (or xprof)
    """
    import jax

    jax.profiler.start_trace(
        logdir, create_perfetto_trace=create_perfetto_trace)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
