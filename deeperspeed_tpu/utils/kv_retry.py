"""Shared retry/degrade policy for coordination-service KV transports.

PR 9's heartbeats (`elasticity/heartbeat.py`) and PR 10's fleet
aggregation (`runtime/fleet.py`) both ride the jax.distributed
coordination-service KV store, and each hand-rolled its own error
handling: the heartbeat monitor counted every error toward coordinator
death with no retry (one gRPC blip = a logged transport error), the
fleet aggregator degraded to own-host scalars on the FIRST error of any
publish/collect (one blip = a silently thinner window). This module is
the one policy both now share:

- `RetryingKVTransport` wraps any transport exposing
  ``publish(peer, payload)`` / ``read_all()`` with **capped exponential
  backoff × uniform jitter** retries (the PR 9 supervisor's backoff
  law): transient coordination-service blips are absorbed before any
  caller-visible failure.
- With ``degrade_to_local=True`` (the fleet posture), attempts
  exhausting on an op logs ONE warning and degrades to an in-process
  `InMemoryTransport` — callers keep own-host behavior (rank 0 still
  aggregates its own summaries) instead of erroring every window. The
  degrade is NOT permanent: a capped-backoff re-probe periodically
  retries the real transport (one call, no retry loop), and the first
  success promotes back — a transient coordination-service blip no
  longer disables fleet scalars/peer health for the rest of the job.
- With ``degrade_to_local=False`` (the heartbeat posture), the final
  error is re-raised: `PeerHealthMonitor.poll_once` MUST see persistent
  failure — its continuous-outage escalation (declare the coordination
  service itself a dead peer after ``fail_after_s``) is the detection
  path, and a silent local fallback would blind it.

Retries sleep at most ``sum(min(base·2^i, cap))`` per op — keep
``attempts`` small on paths polled from daemon threads.
"""

import random
import time

from .logging import logger


def backoff_delay(attempt, base, cap, jitter, rng=None):
    """THE capped-exponential-backoff × uniform-jitter law, shared by
    every retry policy in the tree (this transport wrapper, the PR 9
    restart supervisor, the serving quarantine): delay for 1-based
    retry ``attempt`` is ``min(base · 2^(attempt-1), cap)`` scaled by a
    uniform factor in ``[1 - jitter, 1 + jitter]``. Units are whatever
    ``base``/``cap`` are in (the supervisor uses seconds, the serving
    quarantine milliseconds). ``rng`` needs only ``.random()``."""
    delay = min(float(base) * 2.0 ** (int(attempt) - 1), float(cap))
    if jitter:
        r = random.random() if rng is None else rng.random()
        delay *= 1.0 + float(jitter) * (2.0 * r - 1.0)
    return max(delay, 0.0)


class RetryingKVTransport:
    """Capped-exponential-backoff × jitter retry wrapper over a
    heartbeat/fleet KV transport (see the module docstring for the two
    degrade postures)."""

    def __init__(self, transport, attempts=3, backoff_base_s=0.05,
                 backoff_cap_s=1.0, jitter=0.5, degrade_to_local=False,
                 name="kv", rng=None, sleep=time.sleep,
                 reprobe_base_s=5.0, reprobe_cap_s=300.0,
                 clock=time.monotonic):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if not 0 <= jitter < 1:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.transport = transport
        self.attempts = int(attempts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter = float(jitter)
        self.degrade_to_local = bool(degrade_to_local)
        self.name = str(name)
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._clock = clock
        self._local = None           # set while degraded
        self.retry_count = 0
        self.error_count = 0
        # capped-backoff re-probe of the real transport while degraded:
        # probe intervals follow the shared backoff law (base·2^k up to
        # cap) so a long outage settles at one cheap probe per cap
        # rather than hammering a struggling coordinator
        self.reprobe_base_s = float(reprobe_base_s)
        self.reprobe_cap_s = float(reprobe_cap_s)
        self._reprobe_failures = 0
        self._next_reprobe_at = None
        self.reprobe_count = 0
        self.recovered_count = 0

    @property
    def degraded(self):
        return self._local is not None

    def _backoff_s(self, attempt):
        """Delay before retry `attempt` (1-based): the shared capped
        exponential × jitter law — independent publishers must not
        stampede a recovering coordinator in lockstep."""
        return backoff_delay(attempt, self.backoff_base_s,
                             self.backoff_cap_s, self.jitter, self._rng)

    def _schedule_reprobe(self):
        self._reprobe_failures += 1
        delay = backoff_delay(self._reprobe_failures, self.reprobe_base_s,
                              self.reprobe_cap_s, self.jitter, self._rng)
        self._next_reprobe_at = self._clock() + delay

    def _try_reprobe(self, op, args):
        """While degraded, opportunistically retry the REAL transport
        when the probe deadline has passed — one bare call, no retry
        loop (a dead coordinator must not add attempts × backoff of
        latency to every degraded op). Success promotes back and
        returns the real result; failure re-schedules and returns None
        (caller falls through to the local store)."""
        if self._next_reprobe_at is None or \
                self._clock() < self._next_reprobe_at:
            return None
        self.reprobe_count += 1
        try:
            out = getattr(self.transport, op)(*args)
        except Exception as e:  # noqa: BLE001 - the policy seam
            self.error_count += 1
            self._schedule_reprobe()
            logger.debug(f"{self.name}: re-probe {self.reprobe_count} "
                         f"failed ({type(e).__name__}: {e})")
            return None
        self._local = None
        self._reprobe_failures = 0
        self._next_reprobe_at = None
        self.recovered_count += 1
        logger.warning(
            f"{self.name}: coordination-service KV transport recovered "
            f"after {self.reprobe_count} re-probe(s) — promoting back "
            f"from the local in-memory store")
        return out

    def _call(self, op, *args):
        if self._local is not None:
            out = self._try_reprobe(op, args)
            if out is not None or not self.degraded:
                return out
            return getattr(self._local, op)(*args)
        last = None
        for attempt in range(1, self.attempts + 1):
            try:
                return getattr(self.transport, op)(*args)
            except Exception as e:  # noqa: BLE001 - the policy seam
                last = e
                self.error_count += 1
                if attempt < self.attempts:
                    self.retry_count += 1
                    self._sleep(self._backoff_s(attempt))
        if not self.degrade_to_local:
            raise last
        # single-warning degrade-to-local: further ops run against an
        # in-process store, preserving own-host behavior, until a
        # capped-backoff re-probe finds the real transport healthy
        from ..elasticity.heartbeat import InMemoryTransport
        self._local = InMemoryTransport()
        self._reprobe_failures = 0
        self._schedule_reprobe()
        logger.warning(
            f"{self.name}: coordination-service KV {op} still failing "
            f"after {self.attempts} attempt(s) "
            f"({type(last).__name__}: {last}) — degrading to a local "
            f"in-memory store (this host only; re-probing with capped "
            f"backoff from {self.reprobe_base_s:.0f}s)")
        return getattr(self._local, op)(*args)

    def publish(self, peer, payload):
        return self._call("publish", peer, payload)

    def read_all(self):
        return self._call("read_all")


def wrap_kv_transport(transport, degrade_to_local, name):
    """The standard wrapping both subsystems use (one knob site)."""
    return RetryingKVTransport(transport,
                               degrade_to_local=degrade_to_local,
                               name=name)
