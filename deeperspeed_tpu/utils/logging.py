"""Rank-aware logging (reference: `deepspeed/utils/logging.py`)."""

import logging
import sys

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name="DeeperSpeedTPU", level=logging.INFO):
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s")
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(formatter)
        lg.addHandler(handler)
    return lg


logger = _create_logger()


def _current_rank():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log `message` only on the given process ranks (None or [-1] = all)."""
    my_rank = _current_rank()
    if ranks is None or ranks == [-1] or my_rank in set(ranks):
        logger.log(level, f"[Rank {my_rank}] {message}")
