"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference scales sequence length with block-sparse attention (SURVEY
§5.7) — v0.3.15 predates sequence parallelism. This module is the modern
TPU-native long-context answer, first-class per the build goals:

- **Ring attention** (`ring_attention`): q stays put; k/v chunks rotate
  around the ``seq`` mesh axis via `ppermute` (ICI neighbor hops), with
  online-softmax merging of per-chunk partials — memory per chip is
  O(S/n · S/n) and the full sequence never materializes anywhere.
- **Balanced causal ring** (`ring_attention_balanced`): striped/zigzag
  shard assignment — rank r holds sequence chunks r and 2n-1-r (head +
  tail paired), so every rank carries the same causal workload instead
  of rank 0's shard being almost entirely masked. Off-diagonal ring
  steps then compute exactly the two alive c×c tiles (half the dense
  flops), selected data-dependently so the program is identical on all
  ranks.
- **Ulysses / all-to-all** (`ulysses_attention`): `all_to_all` swaps the
  sharded axis from sequence to heads, runs ordinary (flash) attention on
  full sequences for 1/n of the heads, and swaps back. Cheaper collectives
  when heads ≥ chips.

All are pure functions usable inside `shard_map` over a mesh axis, and
`SequenceParallel` wraps mesh plumbing (including the zigzag permutation
and its inverse) for whole-array callers.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from ..compat import shard_map
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name, causal=True, sm_scale=None,
                   axis_size=None, segment_ids=None):
    """Ring attention inside shard_map: inputs are the local sequence
    shard [B, S/n, H, D]; returns the local output shard.

    Per step t, this chip holds the k/v chunk originating at ring position
    (my_idx - t) mod n and folds its contribution into a running
    flash-style (m, l, acc) online softmax; `ppermute` then forwards k/v
    to the next neighbor. Unrolled over the (static) axis size so XLA
    overlaps each hop with the previous step's matmuls.

    `segment_ids` (local shard [B, S/n] int32, 0 = pad — see
    `runtime.packing`) makes attention intra-document: the k-side ids
    ride the same ring as k/v and each fold ANDs the segment-equality
    mask into the causal keep. A packed document split across ranks
    still attends to all of itself — the ring walks every kv chunk.
    """
    n = axis_size
    if not isinstance(n, int):
        raise ValueError("ring_attention needs a static axis_size")
    b, s_local, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32)
    m_run = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l_run = jnp.zeros((b, h, s_local), jnp.float32)
    acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    k_cur, v_cur = k, v
    seg_cur = segment_ids
    for step in range(n):
        src = (my_idx - step) % n
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_cur.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        keep = None
        if causal:
            rows = jnp.arange(s_local)[:, None] + my_idx * s_local
            cols = jnp.arange(s_local)[None, :] + src * s_local
            keep = rows >= cols
        if segment_ids is not None:
            seg_eq = segment_ids[:, :, None] == seg_cur[:, None, :]
            keep = seg_eq if keep is None else keep[None] & seg_eq
        m_run, l_run, acc = _osm_fold(m_run, l_run, acc, logits, v_cur,
                                      keep)
        if step < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            if seg_cur is not None:
                seg_cur = jax.lax.ppermute(seg_cur, axis_name, perm)

    l_safe = jnp.maximum(l_run, 1e-30)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _osm_fold(m, l, acc, logits, v, mask=None):
    """One online-softmax fold: merge a [B, H, R, C] logits tile (keys'
    values v [B, C, H, D]) into the running (m [B, H, R], l, acc
    [B, R, H, D]) statistics. `mask` is [R, C] (shared across batch) or
    [B, R, C] (segment masks differ per row)."""
    if mask is not None:
        mask = mask[None, None] if mask.ndim == 2 else mask[:, None]
        logits = jnp.where(mask, logits, NEG_INF)
    m_c = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_c)
    p = jnp.exp(logits - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def zigzag_chunk_order(n):
    """Global chunk order of the striped causal shard assignment: the
    sequence splits into 2n chunks and rank r owns chunks (r, 2n-1-r) —
    head and tail paired, so every rank carries the same causal load
    (the plain contiguous split gives rank 0 an almost fully masked
    shard and rank n-1 an almost dense one)."""
    order = []
    for r in range(n):
        order += [r, 2 * n - 1 - r]
    return order


def ring_attention_balanced(q, k, v, axis_name, sm_scale=None,
                            axis_size=None, segment_ids=None):
    """Causal ring attention over ZIGZAG shards inside shard_map: the
    local [B, S/n, H, D] shard holds global chunks (r, 2n-1-r) (see
    `zigzag_chunk_order`; `SequenceParallel` applies the permutation).

    Load balance: pairing head and tail chunks makes each rank's alive
    causal area equal, and each off-diagonal ring step computes exactly
    TWO unmasked c×c tiles instead of the dense 2c×2c four:

    - (tail rows × head kv chunk): always fully alive — the tail chunk
      index 2n-1-r is ≥ n, every kv head chunk index is < n.
    - one of (head rows × head kv) or (tail rows × tail kv), picked by
      whether the kv source rank precedes this rank in the stripe; the
      pick is a data-dependent `where` on equal-shaped tiles so every
      rank runs the same program (no per-rank lowering divergence).

    Step 0 (own kv) folds the dense 2c×2c tile under the static zigzag
    diagonal mask [[tril, 0], [1, tril]]. Total per-step flops are
    rank-independent — the property the contiguous causal ring lacks.

    `segment_ids` (local ZIGZAG shard [B, S/n], 0 = pad) makes attention
    intra-document: ids ride the ring alongside k/v and every fold —
    the step-0 diagonal tile and both off-diagonal tiles — ANDs the
    segment-equality mask into its keep mask. The zigzag permutation
    does not break segment semantics (ids are compared by VALUE, not
    position), so a document straddling the head/tail chunk split still
    attends to all of itself.
    """
    n = axis_size
    if not isinstance(n, int):
        raise ValueError("ring_attention_balanced needs a static axis_size")
    b, s_local, h, d = q.shape
    if s_local % 2:
        raise ValueError("zigzag shards need an even local sequence")
    c = s_local // 2
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32)
    m_run = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l_run = jnp.zeros((b, h, s_local), jnp.float32)
    acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    # step 0: own kv — dense fold under the (rank-independent) zigzag
    # diagonal mask: within-chunk tril on both halves, tail sees all of
    # head (tail positions are globally later than every head position)
    tri = jnp.tril(jnp.ones((c, c), bool))
    mask0 = jnp.concatenate([
        jnp.concatenate([tri, jnp.zeros((c, c), bool)], axis=1),
        jnp.concatenate([jnp.ones((c, c), bool), tri], axis=1),
    ], axis=0)
    logits0 = jnp.einsum("bqhd,bkhd->bhqk", q32, k.astype(jnp.float32),
                         preferred_element_type=jnp.float32) * scale
    mask0 = mask0[None]
    if segment_ids is not None:
        mask0 = mask0 & (segment_ids[:, :, None] ==
                         segment_ids[:, None, :])
    m_run, l_run, acc = _osm_fold(m_run, l_run, acc, logits0, v, mask0)

    k_cur, v_cur = k, v
    seg_cur = segment_ids
    seg_head_q = seg_tail_q = None
    if segment_ids is not None:
        seg_head_q, seg_tail_q = segment_ids[:, :c], segment_ids[:, c:]
    q_head, q_tail = q32[:, :c], q32[:, c:]
    for step in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        if seg_cur is not None:
            seg_cur = jax.lax.ppermute(seg_cur, axis_name, perm)
        k32 = k_cur.astype(jnp.float32)
        k_head, k_tail = k32[:, :c], k32[:, c:]
        v_head, v_tail = v_cur[:, :c], v_cur[:, c:]

        # tile A: tail rows × kv head chunk — always fully alive
        # causally (segments may still mask elements within it)
        m_t, l_t = m_run[:, :, c:], l_run[:, :, c:]
        acc_t = acc[:, c:]
        logits_a = jnp.einsum("bqhd,bkhd->bhqk", q_tail, k_head,
                              preferred_element_type=jnp.float32) * scale
        mask_a = None
        if seg_cur is not None:
            mask_a = seg_tail_q[:, :, None] == seg_cur[:, None, :c]
        m_t, l_t, acc_t = _osm_fold(m_t, l_t, acc_t, logits_a, v_head,
                                    mask_a)

        # tile B: kv source rank src = (my - step) mod n precedes this
        # rank (src < my ⇔ step ≤ my) → head rows × kv head chunk;
        # otherwise tail rows × kv tail chunk. Same-shape `where` picks.
        to_head = (my_idx >= step)
        q_b = jnp.where(to_head, q_head, q_tail)
        k_b = jnp.where(to_head, k_head, k_tail)
        v_b = jnp.where(to_head, v_head, v_tail)
        m_h, l_h = m_run[:, :, :c], l_run[:, :, :c]
        acc_h = acc[:, :c]
        m_sel = jnp.where(to_head, m_h, m_t)
        l_sel = jnp.where(to_head, l_h, l_t)
        acc_sel = jnp.where(to_head, acc_h, acc_t)
        logits_b = jnp.einsum("bqhd,bkhd->bhqk", q_b, k_b,
                              preferred_element_type=jnp.float32) * scale
        mask_b = None
        if seg_cur is not None:
            seg_qb = jnp.where(to_head, seg_head_q, seg_tail_q)
            seg_kb = jnp.where(to_head, seg_cur[:, :c], seg_cur[:, c:])
            mask_b = seg_qb[:, :, None] == seg_kb[:, None, :]
        m_sel, l_sel, acc_sel = _osm_fold(m_sel, l_sel, acc_sel,
                                          logits_b, v_b, mask_b)
        m_h = jnp.where(to_head, m_sel, m_h)
        l_h = jnp.where(to_head, l_sel, l_h)
        acc_h = jnp.where(to_head, acc_sel, acc_h)
        m_t = jnp.where(to_head, m_t, m_sel)
        l_t = jnp.where(to_head, l_t, l_sel)
        acc_t = jnp.where(to_head, acc_t, acc_sel)

        m_run = jnp.concatenate([m_h, m_t], axis=2)
        l_run = jnp.concatenate([l_h, l_t], axis=2)
        acc = jnp.concatenate([acc_h, acc_t], axis=1)

    l_safe = jnp.maximum(l_run, 1e-30)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, attn_fn=None, causal=True,
                      axis_size=None, segment_ids=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism inside
    shard_map: swap sharding seq→heads, run full-sequence attention on
    1/n of the heads, swap back. Requires num_heads % n == 0.

    `segment_ids` (local shard [B, S/n], 0 = pad): after the swap each
    rank holds the FULL sequence for its head slice, so the ids are
    all-gathered along the axis and handed to the attention core
    (`attn_fn` must accept a `segment_ids` kwarg — the default
    `causal_attention` and the segmented flash kernels do)."""
    n = axis_size
    if not isinstance(n, int):
        raise ValueError("ulysses_attention needs a static axis_size")
    b, s_local, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"num heads {h} not divisible by axis size {n}")

    def seq_to_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn_fn is None:
        from ..models.gpt_neox import causal_attention
        attn_fn = partial(causal_attention, use_pallas=True) if causal \
            else None
    if attn_fn is None:
        raise ValueError("non-causal ulysses needs an explicit attn_fn")
    if segment_ids is not None:
        seg_full = jax.lax.all_gather(segment_ids, axis_name, axis=1,
                                      tiled=True)          # [B, S]
        out = attn_fn(qh, kh, vh, segment_ids=seg_full)
    else:
        out = attn_fn(qh, kh, vh)
    return heads_to_seq(out)


class SequenceParallel:
    """Whole-array wrapper: shards [B, S, H, D] over `axis` of `mesh` and
    applies ring or Ulysses attention under shard_map.

    `balance` (causal ring only): zigzag/striped shard assignment so SP
    ranks do equal causal work (`ring_attention_balanced`). Default None
    = auto: balanced whenever the sequence splits into 2n chunks; set
    False to force the contiguous assignment, True to require balancing
    (raises if the sequence does not divide)."""

    def __init__(self, mesh, axis="seq", mode="ring", causal=True,
                 balance=None):
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}")
        if balance and mode != "ring":
            # refuse rather than silently run unbalanced — the explicit
            # request cannot be honored on this mode
            raise ValueError(
                f"balance=True applies to causal ring only, not "
                f"mode={mode!r}")
        self.mesh = mesh
        self.axis = axis
        self.mode = mode
        self.causal = causal
        self.balance = balance
        self.axis_size = int(mesh.shape[axis])

    def _use_balance(self, s):
        if not self.causal:
            if self.balance:
                raise ValueError("balance=True needs a causal ring")
            return False
        if self.axis_size == 1:
            # balanced and contiguous assignments coincide on one rank;
            # honor balance=True as a no-op so device-count-agnostic
            # configs run unchanged in single-device debug runs
            return False
        fits = s % (2 * self.axis_size) == 0
        if self.balance and not fits:
            raise ValueError(
                f"balance=True needs seq {s} divisible by "
                f"2*axis_size={2 * self.axis_size}")
        return fits if self.balance is None else bool(self.balance)

    def __call__(self, q, k, v, segment_ids=None):
        spec = P(None, self.axis, None, None)
        if self.mode == "ring":
            if self._use_balance(q.shape[1]):
                return self._balanced_ring(q, k, v, spec, segment_ids)
            fn = partial(ring_attention, axis_name=self.axis,
                         causal=self.causal, axis_size=self.axis_size)
        elif self.mode == "ulysses":
            fn = partial(ulysses_attention, axis_name=self.axis,
                         causal=self.causal, axis_size=self.axis_size)
        else:
            raise ValueError(f"unknown mode {self.mode!r}")
        if segment_ids is None:
            mapped = shard_map(lambda q, k, v: fn(q, k, v),
                               mesh=self.mesh,
                               in_specs=(spec, spec, spec),
                               out_specs=spec)
            return mapped(q, k, v)
        seg_spec = P(None, self.axis)
        mapped = shard_map(
            lambda q, k, v, seg: fn(q, k, v, segment_ids=seg),
            mesh=self.mesh, in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec)
        return mapped(q, k, v, segment_ids.astype(jnp.int32))

    def _balanced_ring(self, q, k, v, spec, segment_ids=None):
        """Permute the sequence into the zigzag chunk order, run the
        balanced ring, and invert the permutation on the output (the
        gather pair is O(S·H·D) data movement, amortized over the
        O(S²/n·H·D) attention it balances). Segment ids ride the same
        permutation — they are compared by value, so reordering is
        transparent to the intra-document masking."""
        import numpy as np
        n = self.axis_size
        c = q.shape[1] // (2 * n)
        perm = np.concatenate(
            [np.arange(c) + ch * c for ch in zigzag_chunk_order(n)])
        inv = np.argsort(perm)
        fn = partial(ring_attention_balanced, axis_name=self.axis,
                     axis_size=n)
        if segment_ids is None:
            mapped = shard_map(lambda q, k, v: fn(q, k, v),
                               mesh=self.mesh,
                               in_specs=(spec, spec, spec),
                               out_specs=spec)
            out = mapped(*(jnp.take(t, perm, axis=1)
                           for t in (q, k, v)))
            return jnp.take(out, inv, axis=1)
        seg_spec = P(None, self.axis)
        mapped = shard_map(
            lambda q, k, v, seg: fn(q, k, v, segment_ids=seg),
            mesh=self.mesh, in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec)
        out = mapped(*(jnp.take(t, perm, axis=1) for t in (q, k, v)),
                     jnp.take(segment_ids.astype(jnp.int32), perm,
                              axis=1))
        return jnp.take(out, inv, axis=1)
