"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference scales sequence length with block-sparse attention (SURVEY
§5.7) — v0.3.15 predates sequence parallelism. This module is the modern
TPU-native long-context answer, first-class per the build goals:

- **Ring attention** (`ring_attention`): q stays put; k/v chunks rotate
  around the ``seq`` mesh axis via `ppermute` (ICI neighbor hops), with
  online-softmax merging of per-chunk partials — memory per chip is
  O(S/n · S/n) and the full sequence never materializes anywhere.
- **Ulysses / all-to-all** (`ulysses_attention`): `all_to_all` swaps the
  sharded axis from sequence to heads, runs ordinary (flash) attention on
  full sequences for 1/n of the heads, and swaps back. Cheaper collectives
  when heads ≥ chips.

Both are pure functions usable inside `shard_map` over a mesh axis, and
`SequenceParallel` wraps mesh plumbing for whole-array callers.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name, causal=True, sm_scale=None,
                   axis_size=None):
    """Ring attention inside shard_map: inputs are the local sequence
    shard [B, S/n, H, D]; returns the local output shard.

    Per step t, this chip holds the k/v chunk originating at ring position
    (my_idx - t) mod n and folds its contribution into a running
    flash-style (m, l, acc) online softmax; `ppermute` then forwards k/v
    to the next neighbor. Unrolled over the (static) axis size so XLA
    overlaps each hop with the previous step's matmuls.
    """
    n = axis_size
    if not isinstance(n, int):
        raise ValueError("ring_attention needs a static axis_size")
    b, s_local, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32)
    m_run = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l_run = jnp.zeros((b, h, s_local), jnp.float32)
    acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n):
        src = (my_idx - step) % n
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_cur.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        rows = jnp.arange(s_local)[:, None] + my_idx * s_local
        cols = jnp.arange(s_local)[None, :] + src * s_local
        if causal:
            keep = rows >= cols
        else:
            keep = jnp.full((s_local, s_local), True)
        logits = jnp.where(keep[None, None], logits, NEG_INF)

        m_c = jnp.max(logits, axis=-1)                 # [B,H,Sq]
        m_new = jnp.maximum(m_run, m_c)
        p = jnp.exp(logits - m_new[..., None])         # masked → 0
        alpha = jnp.exp(m_run - m_new)
        l_run = l_run * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + \
            jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        m_run = m_new
        if step < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    l_safe = jnp.maximum(l_run, 1e-30)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, attn_fn=None, causal=True,
                      axis_size=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism inside
    shard_map: swap sharding seq→heads, run full-sequence attention on
    1/n of the heads, swap back. Requires num_heads % n == 0."""
    n = axis_size
    if not isinstance(n, int):
        raise ValueError("ulysses_attention needs a static axis_size")
    b, s_local, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"num heads {h} not divisible by axis size {n}")

    def seq_to_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn_fn is None:
        from ..models.gpt_neox import causal_attention
        attn_fn = partial(causal_attention, use_pallas=True) if causal \
            else None
    if attn_fn is None:
        raise ValueError("non-causal ulysses needs an explicit attn_fn")
    out = attn_fn(qh, kh, vh)
    return heads_to_seq(out)


class SequenceParallel:
    """Whole-array wrapper: shards [B, S, H, D] over `axis` of `mesh` and
    applies ring or Ulysses attention under shard_map."""

    def __init__(self, mesh, axis="seq", mode="ring", causal=True):
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}")
        self.mesh = mesh
        self.axis = axis
        self.mode = mode
        self.causal = causal
        self.axis_size = int(mesh.shape[axis])

    def __call__(self, q, k, v):
        spec = P(None, self.axis, None, None)
        if self.mode == "ring":
            fn = partial(ring_attention, axis_name=self.axis,
                         causal=self.causal, axis_size=self.axis_size)
        elif self.mode == "ulysses":
            fn = partial(ulysses_attention, axis_name=self.axis,
                         causal=self.causal, axis_size=self.axis_size)
        else:
            raise ValueError(f"unknown mode {self.mode!r}")
        mapped = shard_map(lambda q, k, v: fn(q, k, v), mesh=self.mesh,
                           in_specs=(spec, spec, spec), out_specs=spec)
        return mapped(q, k, v)
