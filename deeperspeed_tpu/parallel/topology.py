"""N-D cartesian process/chip topology (reference:
`deepspeed/runtime/pipe/topology.py:13-255`).

Pure rank math, no communication. Row-major layout: the *last* axis varies
fastest, so putting `data` (or `model`) last keeps those groups on adjacent
chips — on TPU that means gradient reductions and tensor-parallel collectives
ride high-bandwidth ICI while pipeline hops can cross DCN.

The torch `ProcessGroup` plumbing of the reference is replaced by
`deeperspeed_tpu.parallel.mesh`, which lowers a topology onto a
`jax.sharding.Mesh` with one named axis per topology axis.
"""

from collections import namedtuple
from itertools import product as cartesian_product


class ProcessTopology:
    """Maps n-dimensional cartesian coordinates to linear rank indices.

    Axes are accessed by name; the given axis order defines a row-major
    layout, so ``axes=['x', 'y']`` maps (x, y) and (x, y+1) to adjacent
    ranks.
    """

    def __init__(self, axes, dims):
        if len(axes) != len(dims):
            raise ValueError(f"axes {axes} and dims {dims} length mismatch")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)

        self.mapping = {}
        for global_rank, coord in enumerate(
                cartesian_product(*[range(d) for d in self.dims])):
            self.mapping[self.ProcessCoord(*coord)] = global_rank
        self._coord_of_rank = {r: c for c, r in self.mapping.items()}

    def get_rank(self, **coord_kwargs):
        """Global rank of the process at the given full coordinate."""
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(
                "get_rank() requires a full coordinate; use filter_match() "
                "for slices")
        key = self.ProcessCoord(**coord_kwargs)
        if key not in self.mapping:
            raise KeyError(f"coordinate {coord_kwargs} not in topology")
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_",
                      outer_sep="-"):
        """Checkpoint-style name for a rank, e.g. ``model_00`` (axes in
        ``omit_axes`` are excluded; matches the reference's file naming)."""
        omit = frozenset(omit_axes)
        coord = self.get_coord(rank)
        names = [f"{ax}{inner_sep}{getattr(coord, ax):02d}"
                 for ax in self.axes if ax not in omit]
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        if rank not in self._coord_of_rank:
            raise ValueError(f"rank {rank} not in topology")
        return self._coord_of_rank[rank]

    def get_axis_comm_lists(self, axis):
        """Communicator groups along ``axis``: lists of ranks that agree on
        every coordinate except ``axis``."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for coord in cartesian_product(
                *[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, coord))
            lists.append([
                self.mapping[self.ProcessCoord(**fixed, **{axis: i})]
                for i in range(self.get_dim(axis))
            ])
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks whose coordinates match all given axis=value criteria."""
        return [rank for coord, rank in self.mapping.items()
                if all(getattr(coord, k) == v
                       for k, v in filter_kwargs.items())]

    def get_axis_list(self, axis, idx):
        """Ranks whose coordinate along ``axis`` equals ``idx``."""
        axis_num = self.axes.index(axis)
        return [rank for coord, rank in self.mapping.items()
                if coord[axis_num] == idx]

    def world_size(self):
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


def _prime_factors(n):
    """Prime factorization of a positive integer, smallest first."""
    if n <= 0:
        raise ValueError("Values must be strictly positive.")
    primes = []
    candidate = 2
    while n != 1:
        while n % candidate == 0:
            primes.append(candidate)
            n //= candidate
        candidate += 1
    return primes


class PipeDataParallelTopology(ProcessTopology):
    """Hybrid pipeline+data parallelism; `data` is the fast axis so gradient
    reductions use the highest-bandwidth links."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D hybrid pipeline/model/data parallelism; `model` is the fast axis
    (tensor-parallel collectives are the most latency-sensitive)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


def default_topology(world_size):
    """Split world into pipe×data by alternating prime factors (reference
    `topology.py:290-296`)."""
    num_pp, num_dp = 1, 1
    for idx, prime in enumerate(_prime_factors(world_size)):
        if idx % 2 == 0:
            num_pp *= prime
        else:
            num_dp *= prime
    return PipeDataParallelTopology(num_pp=num_pp, num_dp=num_dp)
