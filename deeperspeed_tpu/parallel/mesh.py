"""Topology → `jax.sharding.Mesh` lowering and the mpu-style grid object.

This replaces the reference's `PipelineParallelGrid`
(`deepspeed/runtime/pipe/topology.py:257-466`): where the reference builds
torch `ProcessGroup`s per dp/pp/mp/slice axis, here each topology axis
becomes a named mesh axis and XLA derives the collective groups from
sharding specs. The grid keeps the same accessor API so engine code (and
external Megatron-style callers) can stay mpu-agnostic.

Canonical axis names: ``pipe``, ``data``, ``model`` (matching the reference
topology names). ZeRO shards over ``data``; tensor parallelism over
``model``; the pipeline executor ppermutes over ``pipe``.
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .topology import ProcessTopology, default_topology

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


def build_mesh(topology=None, devices=None, axes=None, dims=None):
    """Build a Mesh whose linear device order matches the topology's
    row-major rank order, so topology rank i == mesh device i."""
    if devices is None:
        devices = jax.devices()
    if topology is None:
        if axes is None or dims is None:
            topology = default_topology(len(devices))
        else:
            topology = ProcessTopology(axes=axes, dims=dims)
    if topology.world_size() != len(devices):
        raise ValueError(
            f"topology world size {topology.world_size()} != device count "
            f"{len(devices)}")
    dev_array = np.asarray(devices, dtype=object).reshape(topology.dims)
    return Mesh(dev_array, axis_names=tuple(topology.get_axis_names()))


def data_parallel_sharding(mesh, spec=None):
    """Sharding for a batch: leading dim split over every data-like axis."""
    if spec is None:
        spec = PartitionSpec(mesh.axis_names[-1] if DATA_AXIS not in
                             mesh.axis_names else DATA_AXIS)
    return NamedSharding(mesh, spec)


class PipelineParallelGrid:
    """mpu-compatible view of a device mesh.

    Exposes the same accessors as the reference grid
    (`get_data_parallel_world_size`, `get_pipe_parallel_rank`, ...) but
    groups are mesh axes rather than torch process groups. "Ranks" here are
    *chips* (mesh positions); with multi-host meshes the local process sees
    only its addressable shard of each array, which XLA manages.
    """

    def __init__(self, topology=None, devices=None, rank=0):
        if devices is None:
            devices = jax.devices()
        self._topo = topology if topology is not None else \
            default_topology(len(devices))
        self.global_rank = rank
        self.world_size = self._topo.world_size()
        if self.world_size != len(devices):
            raise ValueError(
                f"topology world size {self.world_size} != device count "
                f"{len(devices)}")

        self.mesh = build_mesh(self._topo, devices)

        self.data_parallel_size = max(self._topo.get_dim(DATA_AXIS), 1)
        self.pipe_parallel_size = max(self._topo.get_dim(PIPE_AXIS), 1)
        self.model_parallel_size = max(self._topo.get_dim(MODEL_AXIS), 1)

        self.stage_id = self.get_stage_id()
        self.data_parallel_id = self.get_data_parallel_id()
        self.is_first_stage = self.stage_id == 0
        self.is_last_stage = self.stage_id == self.pipe_parallel_size - 1

        # Rank lists per axis, kept for checkpoint naming and debugging.
        self.dp_groups = self._topo.get_axis_comm_lists(DATA_AXIS)
        self.pipe_groups = self._topo.get_axis_comm_lists(PIPE_AXIS)
        self.model_groups = self._topo.get_axis_comm_lists(MODEL_AXIS)
        self.p2p_groups = self._build_p2p_groups()

    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def get_stage_id(self):
        coord = self._coord()
        return getattr(coord, PIPE_AXIS, 0) if PIPE_AXIS in self._topo.axes \
            else 0

    def get_data_parallel_id(self):
        coord = self._coord()
        return getattr(coord, DATA_AXIS, 0) if DATA_AXIS in self._topo.axes \
            else 0

    def _build_p2p_groups(self):
        """[rank, next-stage buddy] pairs along the pipe axis, wrapping at the
        last stage (reference `topology.py:381-396`)."""
        comm_lists = self._topo.get_axis_comm_lists(PIPE_AXIS)
        if not comm_lists:
            return [[r, r] for r in range(self.world_size)]
        p2p_lists = []
        for rank in range(self.world_size):
            for ranks in comm_lists:
                if rank in ranks:
                    idx = ranks.index(rank)
                    buddy = ranks[(idx + 1) % self.pipe_parallel_size]
                    p2p_lists.append([rank, buddy])
                    break
        return p2p_lists

    def stage_to_global(self, stage_id, **kwargs):
        me = self._coord()
        transform = me._replace(**{PIPE_AXIS: stage_id}, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def topology(self):
        return self._topo

    # mpu-style accessors -------------------------------------------------

    def get_global_rank(self):
        return self.global_rank

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_pipe_parallel_group(self):
        return PIPE_AXIS

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_data_parallel_group(self):
        return DATA_AXIS

    def get_data_parallel_src_rank(self):
        return (self.global_rank // self.data_parallel_size) * \
            self.data_parallel_size

    # "model parallel" in the reference engine sense: everything that is not
    # data parallel (pipe × tensor slicing), used for overflow checks.
    def get_model_parallel_rank(self):
        ranks = sorted(self._topo.get_axis_list(DATA_AXIS,
                                                self.data_parallel_id))
        return ranks.index(self.global_rank)

    def get_model_parallel_world_size(self):
        return self.world_size // self.data_parallel_size

    def get_model_parallel_group(self):
        return tuple(a for a in self._topo.axes if a != DATA_AXIS)

    # Megatron-style tensor slicing axis.
    def get_slice_parallel_rank(self):
        coord = self._coord()
        return getattr(coord, MODEL_AXIS, 0) if MODEL_AXIS in self._topo.axes \
            else 0

    def get_slice_parallel_world_size(self):
        return self.model_parallel_size

    def get_slice_parallel_group(self):
        return MODEL_AXIS
