"""Explicit-dataflow collective schedules.

DeepCompile (arXiv:2504.09983) argues that distributed collectives should
be *scheduled* like a compiler pass — prefetch, bucketing, overlap decided
by the framework — instead of handed to the partitioner to guess. This
module is that pass for the two schedules the engine runs:

1. **Explicit ZeRO-3** (`LayerPlan` + `prefetched_block_scan`): parameters
   live sharded over the ``data`` axis; inside ``shard_map`` the layer
   stack runs as a grouped scan whose body issues **bucketed all-gathers
   `prefetch_depth` layers ahead of compute** in program order, so XLA's
   latency-hiding scheduler overlaps the gather of layer ``i+d`` with the
   matmuls of layer ``i`` (the chunked-overlap discipline the MoE a2a path
   proved). Each group body is `jax.checkpoint`ed, so backward
   **re-gathers** the group's params and the transpose of each
   `all_gather` lands as a **reduce-scatter at the layer-backward
   boundary** — gradients arrive pre-sharded to their owner rank.

2. **Software-pipelined 1F1B** (`pipeline_1f1b_overlapped_ticks`): the
   wire-latency-2 variant of the 1F1B tick loop
   (`parallel/pipeline_spmd.pipeline_1f1b_ticks`): each tick FIRST issues
   the `ppermute` of the previous tick's boundary payloads, THEN runs the
   stage compute — activation/grad transfers overlap stage compute at the
   cost of 2·(S-1) extra fill/drain ticks (`bubble_fraction` quantifies
   the trade). Selected by ``pipeline.comm_overlap``.

The shared ``ScheduleConfig`` (parsed from ``zero_optimization.schedule``)
carries the ZeRO-gather knobs: `prefetch_depth` is the layers-ahead
window, `bucket_mb` bounds each all-gather's payload, `group_layers` is
the remat/prefetch window (gathered params live at most one group, and
prefetch resets at group boundaries). The pipeline's
``pipeline.comm_overlap`` flag applies the same double-buffer discipline
to the p2p wire — a fixed depth-1 prefetch (wire latency 2); it does not
read `prefetch_depth` (deeper wire pipelining has no payoff: each tick
produces exactly one boundary buffer).
"""

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..runtime.config_utils import DeepSpeedConfigError

SCHEDULE_MODES = ("gspmd", "explicit")


@dataclass(frozen=True)
class ScheduleConfig:
    """Knobs of the explicit collective schedule (the
    ``zero_optimization.schedule`` block; shared by the ZeRO-3 gather
    schedule and the pipeline comm-overlap path)."""
    mode: str = "gspmd"          # "gspmd" (partitioner) | "explicit"
    prefetch_depth: int = 1      # layers gathered ahead of compute
    bucket_mb: float = 32.0      # max bytes per all-gather bucket
    group_layers: int = 4        # layers per remat/prefetch group
    # remat the gather groups: backward RE-GATHERS params (gathered
    # weights never outlive their group — the ZeRO-3 memory story).
    # False keeps the gathered buffers as backward residuals instead —
    # ~one full gathered param copy of extra live memory in exchange
    # for no recompute (apples-to-apples with a no-remat DDP run).
    remat: bool = True

    @property
    def bucket_bytes(self):
        return int(self.bucket_mb * 1024 * 1024)


# ---------------------------------------------------------------------------
# per-leaf placement (how one array is stored across the data axis)
# ---------------------------------------------------------------------------

REPLICATED, DIM_SHARDED, FLAT_SHARDED = "replicated", "dim", "flat"


class LeafPlacement:
    """Static description of how one param leaf rests on the dp axis:
    ``replicated`` (persistence-threshold smalls), ``dim`` (one natural
    dim carries the data axis), or ``flat`` (stored as a padded 1-D
    buffer sharded over data — ragged leaves, see
    `runtime.zero.partition_parameters.FlatPad`)."""

    __slots__ = ("kind", "dim", "pad", "shape", "dtype", "local_shape",
                 "size")

    def __init__(self, kind, shape, dtype, world, dim=None, pad=None):
        self.kind = kind
        self.dim = dim
        self.pad = pad
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        if kind == DIM_SHARDED:
            local = list(shape)
            if local[dim] % world:
                raise ValueError(
                    f"dim {dim} of {tuple(shape)} does not divide the dp "
                    f"world {world}")
            local[dim] //= world
            self.local_shape = tuple(local)
        elif kind == FLAT_SHARDED:
            if pad.padded % world:
                raise ValueError(
                    f"flat-padded length {pad.padded} does not divide "
                    f"the dp world {world}")
            self.local_shape = (pad.padded // world,)
        else:
            self.local_shape = tuple(shape)
        self.size = int(np.prod(self.local_shape)) if self.local_shape \
            else 1

    @property
    def gathered(self):
        return self.kind != REPLICATED

    def __repr__(self):
        return (f"LeafPlacement({self.kind}, shape={self.shape}, "
                f"dim={self.dim})")


def leaf_placement(shape, dtype, spec, pad, axis_name, world):
    """Classify one leaf from its engine-side PartitionSpec + pad info.
    Only the data axis may appear in ``spec`` — an explicit schedule over
    a tensor/expert-parallel leaf is not supported here."""
    if pad:
        return LeafPlacement(FLAT_SHARDED, pad.shape, dtype, world,
                             pad=pad)
    dims = []
    for d, part in enumerate(spec):
        if part is None:
            continue
        axes = part if isinstance(part, tuple) else (part,)
        for a in axes:
            if a != axis_name:
                raise DeepSpeedConfigError(
                    f"explicit schedule supports pure data-parallel "
                    f"placements; leaf spec {spec} uses mesh axis {a!r}")
        dims.append(d)
    if not dims:
        return LeafPlacement(REPLICATED, shape, dtype, world)
    if len(dims) > 1:
        raise DeepSpeedConfigError(
            f"leaf spec {spec} shards more than one dim over the data "
            f"axis; the explicit schedule expects at most one")
    return LeafPlacement(DIM_SHARDED, shape, dtype, world, dim=dims[0])


def gather_leaf(local, placement, axis_name, world):
    """All-gather ONE leaf's local shard back to its full natural shape
    (embed / head / any non-layer leaf). Replicated leaves pass through."""
    if placement.kind == REPLICATED:
        return local
    pieces = jax.lax.all_gather(jnp.ravel(local), axis_name, tiled=False)
    return _reassemble(pieces, placement, world)


def _reassemble(pieces, placement, world):
    """[world, size] rank-major pieces -> full natural-shaped leaf."""
    if placement.kind == FLAT_SHARDED:
        flat = pieces.reshape(-1)[:placement.pad.numel]
        return flat.reshape(placement.shape)
    k = placement.dim
    stacked = pieces.reshape((world,) + placement.local_shape)
    # rank-major concat along dim k == the NamedSharding shard order
    moved = jnp.moveaxis(stacked, 0, k)
    return moved.reshape(placement.shape)


# ---------------------------------------------------------------------------
# layer gather plan: bucketing math + traced gather/rebuild
# ---------------------------------------------------------------------------

class LayerPlan:
    """Gather plan for ONE transformer layer's parameter pytree.

    The sharded leaves' local shards concatenate (raveled, in flatten
    order) into one [S] row per layer; `buckets` split that row into
    <= ``bucket_bytes`` chunks, each all-gathered as its own collective
    (the DeepCompile bucketing knob: one huge gather serializes behind
    itself; many tiny ones are latency-bound). The last bucket absorbs
    the non-divisible tail. `rebuild` reassembles the gathered [world, S]
    buffer plus the replicated leaves into the natural block pytree.
    """

    def __init__(self, template, specs, pads, axis_name, world,
                 bucket_bytes):
        leaves, self.treedef = jax.tree_util.tree_flatten(template)
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(
                x, jax.sharding.PartitionSpec))
        pad_leaves = jax.tree_util.tree_leaves(pads)
        if not (len(leaves) == len(spec_leaves) == len(pad_leaves)):
            raise ValueError(
                f"template/specs/pads disagree: {len(leaves)} vs "
                f"{len(spec_leaves)} vs {len(pad_leaves)} leaves")
        self.axis_name = axis_name
        self.world = int(world)
        self.placements = [
            leaf_placement(np.shape(l), np.result_type(l), s,
                           p or None, axis_name, self.world)
            for l, s, p in zip(leaves, spec_leaves, pad_leaves)]

        # concat layout of the gathered leaves' shards
        self.offsets = []
        off = 0
        dtypes = set()
        for pl in self.placements:
            if pl.gathered:
                self.offsets.append(off)
                off += pl.size
                dtypes.add(pl.dtype)
            else:
                self.offsets.append(None)
        self.shard_size = off            # S: per-rank elements per layer
        if len(dtypes) > 1:
            raise ValueError(
                f"gathered leaves of one layer must share a dtype for "
                f"bucketed gathers; got {sorted(map(str, dtypes))}")
        self.dtype = dtypes.pop() if dtypes else jnp.dtype(jnp.float32)
        self.buckets = plan_buckets(self.shard_size,
                                    self.dtype.itemsize, bucket_bytes)

    @property
    def n_replicated(self):
        return sum(1 for pl in self.placements if not pl.gathered)

    # -- traced helpers ----------------------------------------------------

    def split_leaves(self, leaves):
        """Flatten-order leaves -> (gathered shards, replicated leaves)."""
        gath = [l for l, pl in zip(leaves, self.placements)
                if pl.gathered]
        rep = [l for l, pl in zip(leaves, self.placements)
               if not pl.gathered]
        return gath, rep

    def concat_shards(self, leaves):
        """This layer's flatten-order leaves -> one [S] row of the
        gathered leaves' raveled local shards (None if all replicated)."""
        parts, _ = self.split_leaves(leaves)
        parts = [jnp.ravel(l) for l in parts]
        if not parts:
            return None
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def gather_row(self, row):
        """Bucketed all-gather of one layer row: each bucket is its own
        collective -> [world, S]."""
        pieces = [
            jax.lax.all_gather(
                jax.lax.slice_in_dim(row, start, start + size, axis=0),
                self.axis_name, tiled=False)
            for start, size in self.buckets]
        return pieces[0] if len(pieces) == 1 else \
            jnp.concatenate(pieces, axis=1)

    def rebuild(self, gathered, rep_leaves):
        """Gathered [world, S] buffer + this layer's replicated leaves
        (in flatten order of the replicated subset) -> natural block
        pytree."""
        out = []
        rep_iter = iter(rep_leaves)
        for pl, off in zip(self.placements, self.offsets):
            if not pl.gathered:
                out.append(next(rep_iter))
                continue
            piece = jax.lax.slice_in_dim(gathered, off, off + pl.size,
                                         axis=1)
            out.append(_reassemble(piece, pl, self.world))
        return jax.tree_util.tree_unflatten(self.treedef, out)


def plan_buckets(shard_size, itemsize, bucket_bytes):
    """[(start, size)] chunks of a [shard_size] row, each at most
    ``bucket_bytes`` big; the final bucket takes the ragged tail. A
    non-positive bucket size is one whole-row bucket."""
    if shard_size <= 0:
        return []
    elems = max(1, int(bucket_bytes) // max(1, int(itemsize)))
    if bucket_bytes <= 0 or elems >= shard_size:
        return [(0, shard_size)]
    out = []
    start = 0
    while start < shard_size:
        size = min(elems, shard_size - start)
        out.append((start, size))
        start += size
    return out


# ---------------------------------------------------------------------------
# tiered-offload row layout (host DRAM/NVMe <-> the gather schedule)
# ---------------------------------------------------------------------------

def offload_layer_plan(template, axis_name, world, bucket_bytes):
    """`LayerPlan` for the tiered-offload executor: EVERY leaf stored
    flat-padded and sharded over the data axis, so a segment's host
    store is one uniform rank-major row (`pack_plan_rows`) and the
    device side reuses the explicit schedule's bucketed `gather_row` /
    `rebuild` unchanged. ``template`` must carry real shapes/dtypes
    (the compute-dtype host params)."""
    from ..runtime.zero.partition_parameters import FlatPad

    leaves, treedef = jax.tree_util.tree_flatten(template)

    def pad_of(l):
        numel = int(np.prod(np.shape(l))) if np.shape(l) else 1
        padded = -(-numel // world) * world
        return FlatPad(np.shape(l), numel, padded)

    pads = treedef.unflatten([pad_of(l) for l in leaves])
    specs = treedef.unflatten([jax.sharding.PartitionSpec(axis_name)
                               for _ in leaves])
    return LayerPlan(template, specs, pads, axis_name, world, bucket_bytes)


def pack_plan_rows(plan, leaves):
    """Flatten-order natural host leaves -> ONE rank-major [world * S]
    row (the tiered-offload host/NVMe storage layout): uploading it with
    a P(data) sharding hands each device exactly its `concat_shards`
    local row, so `gather_row` + `rebuild` reproduce the natural leaves
    bit-exactly. Pad tails are zero."""
    world = plan.world
    blocks = []
    for l, pl in zip(leaves, plan.placements):
        if not pl.gathered:
            raise ValueError("pack_plan_rows requires an offload_layer_plan "
                             "(every leaf flat-sharded)")
        flat = np.ravel(np.asarray(l))
        padded = np.zeros(pl.pad.padded, flat.dtype)
        padded[:flat.size] = flat
        blocks.append(padded.reshape(world, -1))
    return np.hstack(blocks).reshape(-1)


def unpack_plan_row(plan, row):
    """Inverse of `pack_plan_rows`: rank-major [world * S] row ->
    flatten-order natural numpy leaves (copies)."""
    mat = np.asarray(row).reshape(plan.world, plan.shard_size)
    out = []
    for pl, off in zip(plan.placements, plan.offsets):
        piece = mat[:, off:off + pl.size].reshape(-1)[:pl.pad.numel]
        out.append(np.array(piece).reshape(pl.pad.shape))
    return out


def plan_valid_mask(plan):
    """Static [world, S] 0/1 mask of REAL lanes in a layer's gathered
    row: flat-padded leaves contribute zeros past their natural numel
    (rank-major layout — lane (r, off + i) is flat element r·size + i).
    Their cotangents are exact zeros (`rebuild` slices them away), and
    the compressed transport must keep them zero — sign(0) = +scale
    would otherwise pollute grad norms and the flat-padded Adam tails."""
    mask = np.ones((plan.world, plan.shard_size), np.float32)
    for pl, off in zip(plan.placements, plan.offsets):
        if not pl.gathered or pl.kind != FLAT_SHARDED:
            continue
        size = pl.size
        flat_idx = (np.arange(plan.world)[:, None] * size
                    + np.arange(size)[None, :])
        mask[:, off:off + size] = (flat_idx < pl.pad.numel)
    return mask


def make_ef_gather(plan, packed=None):
    """Wrap `plan.gather_row` in a `custom_vjp` whose BACKWARD replaces
    the plain `psum_scatter` transpose with the error-feedback
    sign-compressed reduce-scatter (`runtime.comm.compressed`): the
    cotangent of the gathered [world, S] buffer is exactly this rank's
    full-size gradient contribution, i.e. the tensor 1-bit Adam
    compresses on the DP wire.

    The updated error buffer leaves the backward as the COTANGENT of
    the error input — differentiate the loss w.r.t. (params, ef) and
    the ef "gradient" IS the advanced error-feedback state (the
    cotangent-smuggling idiom; no side channel exists out of a
    transpose). Error state is fp32 regardless of the wire dtype.
    Flat-pad lanes are masked out of the quantization scale and pinned
    to zero (`plan_valid_mask`).

    ``packed`` selects the 8-signs/byte wire (None defers to the
    module default pinned by `comm.compressed.configure_packed_wire`).
    """
    from ..runtime.comm.compressed import compressed_reduce_scatter

    mask = plan_valid_mask(plan)
    valid = None if mask.all() else jnp.asarray(mask)

    @jax.custom_vjp
    def gather_ef(row, werr):
        return plan.gather_row(row)

    def fwd(row, werr):
        return plan.gather_row(row), werr

    def bwd(werr, g):
        out, new_err = compressed_reduce_scatter(
            g, werr, plan.axis_name, plan.world, valid=valid,
            packed=packed)
        return out.astype(plan.dtype), new_err

    gather_ef.defvjp(fwd, bwd)
    return gather_ef


def _segment_sizes(n_layers, n_groups):
    """As-equal-as-possible group sizes (mirror of
    models.gpt_neox.segment_sizes, kept local to avoid a models import
    cycle)."""
    n = max(1, min(int(n_groups), n_layers))
    return [n_layers // n + (1 if i < n_layers % n else 0)
            for i in range(n)]


def make_group_body(block_fn, plan, depth, has_rows=True, gather_fn=None):
    """One remat/prefetch group of uniform layers: python-unrolled, with
    bucketed gathers issued ``depth`` layers ahead in program order (the
    double-buffer XLA's latency-hiding scheduler overlaps with the layer
    matmuls). Shared by `prefetched_block_scan` (in-jit scan over groups)
    and the tiered-offload executor (host loop over per-group programs —
    `runtime/zero/offload_engine.py`), so the two schedules cannot drift.

    Returns ``group_body(x, rows_g, rep_g) -> x`` where ``rows_g`` is a
    list of g per-layer [S] shard rows (or Nones when the plan has no
    gathered leaves) and ``rep_g`` a list of g replicated-leaf lists.
    ``gather_fn`` overrides the per-row gather (the error-feedback
    compressed-gradient path passes (row, werr) entries through
    `make_ef_gather`)."""
    gather = gather_fn or plan.gather_row

    def group_body(x, rows_g, rep_g):
        g = len(rep_g)
        d = min(depth, g)
        gathered = {}
        if has_rows:
            for j in range(d):
                gathered[j] = gather(rows_g[j])
        for i in range(g):
            if has_rows and i + d < g:
                gathered[i + d] = gather(rows_g[i + d])
            bp = plan.rebuild(gathered.pop(i) if has_rows else None,
                              rep_g[i])
            x = block_fn(bp, x)
        return x

    return group_body


def prefetched_block_scan(block_fn, x, layer_leaves, plan, n_layers,
                          prefetch_depth, group_layers, policy=None,
                          remat=True, ef=None):
    """Run ``n_layers`` uniform blocks over dp-sharded params with the
    explicit gather schedule.

    Args (inside shard_map over ``plan.axis_name``):
      block_fn: (block_params, x) -> x, the layer body.
      layer_leaves: per-layer lists of LOCAL leaves (flatten order of
        the plan's template): sharded leaves are shards, replicated
        leaves full.
      prefetch_depth: gathers issued this many layers ahead of compute,
        clamped to the group size (a depth past the remat group cannot
        be honored — gathered params live at most one group).
      group_layers: layers per `jax.checkpoint` group. Residuals per
        group are the boundary carry only, so backward RE-GATHERS the
        group's params (and the gather transposes place each grad
        shard via reduce-scatter at the layer-backward boundary).
      policy: optional jax.checkpoint policy for the group bodies.
      remat: False skips the group checkpoint — backward consumes the
        gathered buffers saved as scan residuals (no re-gather, no
        recompute, ~one gathered param copy of extra live memory). The
        grad reduce-scatters still come from the gather transposes.
      ef: optional [n_layers, world, S] error-feedback state (TRACED,
        part of the caller's differentiated inputs): gathers route
        through `make_ef_gather`, whose backward swaps the psum_scatter
        transpose for the sign-compressed reduce-scatter — the advanced
        error state comes back as the cotangent of ``ef``.

    Groups of equal size ride an outer `lax.scan` (compile O(group), not
    O(L)); ragged layer counts fall back to a Python loop over <= 2
    distinct group shapes.
    """
    depth = max(1, int(prefetch_depth))
    split = [plan.split_leaves(lv) for lv in layer_leaves]
    rows = [plan.concat_shards(lv) for lv in layer_leaves]
    rep_by_layer = [rep for _, rep in split]
    has_rows = bool(rows) and rows[0] is not None
    gather_fn = None
    if ef is not None:
        if not has_rows:
            raise ValueError(
                "gradient compression needs gathered (dp-sharded) "
                "leaves; this plan holds only replicated leaves")
        ef_g = make_ef_gather(plan)
        gather_fn = lambda entry: ef_g(*entry)  # noqa: E731
    group_body = make_group_body(block_fn, plan, depth, has_rows=has_rows,
                                 gather_fn=gather_fn)

    sizes = _segment_sizes(n_layers, -(-n_layers // max(1,
                                                        int(group_layers))))
    uniform = len(set(sizes)) == 1

    if uniform and len(sizes) > 1:
        g = sizes[0]
        n_groups = len(sizes)
        stacked_rows = (jnp.stack(rows).reshape(
            (n_groups, g, plan.shard_size)) if has_rows
            else jnp.zeros((n_groups, g, 0), plan.dtype))
        # replicated leaves stacked over layers -> [n_groups, g, ...]
        stacked_rep = [
            jnp.stack([rep_by_layer[i][k] for i in range(n_layers)]
                      ).reshape((n_groups, g)
                                + np.shape(rep_by_layer[0][k]))
            for k in range(plan.n_replicated)]

        def rows_of(rg, eg):
            if eg is None:
                return [rg[j] for j in range(g)]
            return [(rg[j], eg[j]) for j in range(g)]

        body = (lambda x, rg, eg, lg: group_body(
            x, rows_of(rg, eg),
            [[lv[i] for lv in lg] for i in range(g)]))
        ck = jax.checkpoint(body, policy=policy) if remat else body

        if ef is not None:
            stacked_ef = ef.reshape((n_groups, g) + ef.shape[1:])

            def scan_body(carry, xs):
                rg, eg, lg = xs
                return ck(carry, rg, eg, lg), None

            return jax.lax.scan(
                scan_body, x, (stacked_rows, stacked_ef, stacked_rep))[0]

        def scan_body(carry, xs):
            rg, lg = xs
            return ck(carry, rg, None, lg), None

        return jax.lax.scan(scan_body, x, (stacked_rows, stacked_rep))[0]

    # ragged (or single-group) layer counts: python loop over groups
    idx = 0
    ck = jax.checkpoint(group_body, policy=policy) if remat else group_body
    for size in sizes:
        entries = rows[idx:idx + size]
        if ef is not None:
            entries = [(rows[i], ef[i]) for i in range(idx, idx + size)]
        x = ck(x, entries, rep_by_layer[idx:idx + size])
        idx += size
    return x


# ---------------------------------------------------------------------------
# 1F1B with software-pipelined p2p (wire latency 2)
# ---------------------------------------------------------------------------

def bubble_fraction(n_stages, n_micro, wire_latency=1):
    """Analytic 1F1B bubble fraction: fill+drain ticks over total.
    ``wire_latency`` 1 is the classic schedule (transfer serialized with
    compute); 2 is the comm-overlap schedule (transfers hidden behind
    compute, fill/drain doubled)."""
    w = int(wire_latency)
    s, m = int(n_stages), int(n_micro)
    if m <= 0:
        return 0.0
    return w * (s - 1) / (m + w * (s - 1))


def dcn_exposed_crossings(n_boundaries, n_micro, wire_latency=1,
                          pipelined=True):
    """Schedule-aware EXPOSED cross-slice DCN crossings per optimizer
    step — the count the `dcn_delay` fault kind charges host-side
    latency for (docs/multislice.md).

    The model mirrors `bubble_fraction`'s wire treatment:

    * classic wire (``wire_latency`` 1): every hop is serialized with
      compute, so each of ``n_micro`` micro-batches exposes each DCN
      boundary once forward and once backward — ``2 * b * m``.
    * overlapped wire (``wire_latency`` >= 2): steady-state transfers
      hide behind stage compute; only the fill/drain hops are exposed
      — ``2 * b`` regardless of micro count.
    * data-axis split (``pipelined`` False): the dp reduction ring
      crosses every boundary twice per step (reduce + gather phases)
      — ``2 * b``.
    """
    b = int(n_boundaries)
    if b <= 0:
        return 0
    if not pipelined or int(wire_latency) >= 2:
        return 2 * b
    return 2 * b * int(n_micro)


def pipeline_1f1b_overlapped_ticks(stage_apply, diff_args, buf_template,
                                   n_stages, n_micro, axis_name, rng,
                                   fp32_comm=None):
    """`pipeline_1f1b_ticks` with the inter-stage wire double-buffered:
    each tick FIRST issues the ppermute of the PREVIOUS tick's boundary
    payloads (no data dependence on this tick's compute, so XLA overlaps
    transfer with the stage matmuls), then computes. The wire gains one
    tick of latency, so the clock relations stretch:

      forward  of micro m on stage s at t = 2s + 2m          (even ticks)
      backward of micro m on stage s at t = 4S - 2s + 2m - 3 (odd ticks)

    Fill/drain grows from 2(S-1) to 4(S-1) half-ticks — `bubble_fraction`
    with wire_latency=2 — in exchange for p2p transfers that cost ~zero
    wall-clock in steady state. Same contract as `pipeline_1f1b_ticks`:
    returns (mean loss on the last stage, fp32 grad accumulators).
    """
    from ..runtime.pipe import p2p

    stage = jax.lax.axis_index(axis_name)
    S, M = n_stages, n_micro
    D = min(2 * S - 1, M)
    total = 2 * (M + 2 * (S - 1))
    buf0 = jnp.zeros(buf_template.shape, buf_template.dtype)
    gacc0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), diff_args)

    def tick(carry, t):
        fwd_wire, bwd_wire, fwd_in, bwd_in, stash, gacc, loss_acc = carry
        is_fwd = (t % 2) == 0

        # --- transfers of LAST tick's outputs, issued before compute ----
        # down-wire payloads are produced at even ticks (forwards of
        # stages 0..S-2), up-wire at odd ticks (backwards of stages
        # 1..S-1) — so each tick runs exactly ONE live ppermute, gated
        # off entirely outside its useful range (bubble bandwidth).
        down_live = jnp.logical_not(is_fwd) & (t <= 2 * S + 2 * M - 5)
        up_live = is_fwd & (t >= 2 * S) & (t <= 4 * S + 2 * M - 6)
        fwd_in_next = jax.lax.cond(
            down_live,
            lambda v: p2p.send_to_next(v, axis_name, S,
                                       fp32_comm=fp32_comm),
            lambda v: jnp.zeros_like(v), fwd_wire)
        bwd_in_next = jax.lax.cond(
            up_live,
            lambda v: p2p.send_to_prev(v, axis_name, S,
                                       fp32_comm=fp32_comm),
            lambda v: jnp.zeros_like(v), bwd_wire)

        # --- this tick's compute ---------------------------------------
        tf = t - 2 * stage
        m_f = jnp.clip(tf // 2, 0, M - 1)
        valid_f = is_fwd & (tf >= 0) & (tf <= 2 * (M - 1))
        tb = t - (4 * S - 2 * stage - 3)
        m_b = jnp.clip(tb // 2, 0, M - 1)
        valid_b = jnp.logical_not(is_fwd) & (tb >= 0) & \
            (tb <= 2 * (M - 1))

        def fwd_tick(fwd_in, bwd_in, stash, gacc):
            y, l = stage_apply(diff_args, fwd_in, m_f, rng)
            slot = m_f % D
            keep = jax.lax.dynamic_index_in_dim(stash, slot, 0,
                                                keepdims=False)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(valid_f, fwd_in, keep), slot, 0)
            return y, buf0, l.astype(jnp.float32), stash, gacc

        def bwd_tick(fwd_in, bwd_in, stash, gacc):
            x = jax.lax.dynamic_index_in_dim(stash, m_b % D, 0,
                                             keepdims=False)
            cot_y = jnp.where(stage == S - 1, jnp.zeros_like(bwd_in),
                              bwd_in)
            cot_l = jnp.asarray(1.0 / M, jnp.float32)
            _, pull = jax.vjp(
                lambda args, xx: stage_apply(args, xx, m_b, rng),
                diff_args, x)
            args_bar, x_bar = pull((cot_y.astype(buf_template.dtype),
                                    cot_l))
            gacc = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(valid_b,
                                           g.astype(jnp.float32), 0.0),
                gacc, args_bar)
            return buf0, x_bar, jnp.asarray(0.0, jnp.float32), stash, gacc

        y_out, xbar_out, l, stash, gacc = jax.lax.cond(
            is_fwd, fwd_tick, bwd_tick, fwd_in, bwd_in, stash, gacc)
        loss_acc = loss_acc + jnp.where(
            valid_f & (stage == S - 1), l, 0.0)
        return (y_out, xbar_out, fwd_in_next, bwd_in_next, stash, gacc,
                loss_acc), None

    stash0 = jnp.zeros((D,) + buf_template.shape, buf_template.dtype)
    carry0 = (buf0, buf0, buf0, buf0, stash0, gacc0,
              jnp.asarray(0.0, jnp.float32))
    (_, _, _, _, _, gacc, loss_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(total))
    return loss_acc / M, gacc
