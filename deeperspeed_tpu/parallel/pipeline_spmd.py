"""Compiled SPMD pipeline executor.

This is the TPU lowering of the reference's pipeline engine
(`deepspeed/runtime/pipe/engine.py` + `schedule.py`): instead of a host
loop interpreting Send/Recv/Forward/Backward instructions per stage, the
whole schedule becomes ONE jitted program under `shard_map` over the
``pipe`` mesh axis:

- every stage runs the same program on its shard of a stacked layer
  parameter pytree (leaves [L, ...] sharded over ``pipe`` on dim 0);
- micro-batches flow stage-to-stage via `ppermute` (XLA
  collective-permute riding ICI/DCN);
- the fill/steady/drain structure is a `lax.scan` over
  ``n_micro + n_stages - 1`` ticks (GPipe-style; differentiating through
  the scan yields the reverse-order backward schedule automatically, with
  `jax.checkpoint` on the stage body bounding activation memory);
- loss is computed by the last stage and broadcast with a masked psum —
  the analogue of `_aggregate_total_loss` (`pipe/engine.py:559`).

Use `pipeline_loss_fn` to build an engine-compatible loss from (embed_fn,
stage_fn, head_fn) triples; `GPTNeoXPipeSPMD` wires it for the flagship
model.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import PIPE_AXIS


def spmd_pipeline(stage_fn, stage_params, x_micro, axis_name, n_stages,
                  n_micro, remat=True, fp32_comm=None):
    """Run the pipeline body inside shard_map.

    Args:
      stage_fn: (stage_params, x) -> y; this stage's layer stack.
      stage_params: pytree whose leaves lead with the local layer dim.
      x_micro: [M, mb, ...] micro-batched stage-0 inputs (replicated).
      fp32_comm: upcast bf16/fp16 activations to fp32 for the inter-stage
        wire (fork feature, reference `pipe/p2p.py:31-62`); the backward
        ppermute of the transposed program inherits the same precision.
        None (default) defers to `p2p.configure(...)`'s module setting —
        which `PipelineEngine.__init__` sets from the `fp32_allreduce`
        config before the first compile.
    Returns [M, mb, ...] outputs, valid on the LAST stage (others carry
    bubble garbage — mask downstream).
    """
    from ..runtime.pipe import p2p

    stage = jax.lax.axis_index(axis_name)
    total_ticks = n_micro + n_stages - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def tick(carry, t):
        buf, outputs = carry
        idx = jnp.clip(t, 0, n_micro - 1)
        inject = jax.lax.dynamic_index_in_dim(x_micro, idx, 0,
                                              keepdims=False)
        x = jnp.where(stage == 0, inject.astype(buf.dtype), buf)
        y = body(stage_params, x)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        # select, NOT an arithmetic blend: fill-tick computations run on
        # garbage buffers and may be NaN/Inf, which a blend would
        # propagate into the real outputs (0*NaN = NaN)
        write = t >= n_stages - 1
        current = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                               keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, current), out_idx, 0)
        buf_next = p2p.send_to_next(y, axis_name, n_stages,
                                    fp32_comm=fp32_comm)
        return (buf_next, outputs), None

    mb_shape = x_micro.shape[1:]
    y_shape = jax.eval_shape(
        lambda p, x: stage_fn(p, x), stage_params,
        jax.ShapeDtypeStruct(mb_shape, x_micro.dtype))
    buf0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    outputs0 = jnp.zeros((n_micro,) + y_shape.shape, y_shape.dtype)

    (_, outputs), _ = jax.lax.scan(tick, (buf0, outputs0),
                                   jnp.arange(total_ticks))
    return outputs


def last_stage_value(value, axis_name, n_stages):
    """Broadcast a last-stage scalar/array to every stage (masked psum)."""
    stage = jax.lax.axis_index(axis_name)
    masked = jnp.where(stage == n_stages - 1, value,
                       jnp.zeros_like(value))
    return jax.lax.psum(masked, axis_name)


def pipeline_loss_fn(embed_fn, stage_fn, head_loss_fn, mesh, n_micro,
                     axis_name=PIPE_AXIS, remat=True, fp32_comm=None,
                     data_axis=None, blocks_specs=None, embed_specs=None,
                     head_specs=None):
    """Build loss(params, batch, rng) running the block stack pipelined.

    params = {"embed": ..., "blocks": stacked leaves [L, ...],
              "head": ...}; blocks are sharded over (axis_name,) on dim 0
    — or per `blocks_specs` (a matching pytree of PartitionSpecs, e.g.
    `block_param_specs_tp` for tensor-parallel slices). batch =
    (tokens [B, S], labels). The global batch splits into `n_micro`
    micro-batches along dim 0.

    With `data_axis` set (and present in the mesh), the batch is consumed
    sharded over that axis and the loss is the data-parallel mean — a
    full dp×pp(×tp) step in one program; shard_map's transpose inserts
    the gradient psums over every axis a parameter is replicated on.
    """
    n_stages = int(mesh.shape[axis_name])
    dp_active = (data_axis is not None and data_axis in mesh.axis_names
                 and int(mesh.shape[data_axis]) > 1)

    def loss_fn(params, batch, rng=None):
        tokens, labels = batch

        def inner(blocks_local, embed_params, head_params, tokens, labels):
            b = tokens.shape[0]
            if b % n_micro != 0 or b < n_micro:
                raise ValueError(
                    f"per-data-rank batch {b} must split into n_micro="
                    f"{n_micro} micro-batches (global batch / dp size "
                    f"must be a multiple of n_micro)")
            mb = b // n_micro
            tok_micro = tokens.reshape((n_micro, mb) + tokens.shape[1:])
            lab_micro = labels.reshape((n_micro, mb) + labels.shape[1:])
            # Embedding is cheap; every stage computes it replicated so
            # stage 0's injections exist locally (no host scatter).
            x_micro = jax.vmap(lambda t: embed_fn(embed_params, t))(
                tok_micro)

            outputs = spmd_pipeline(stage_fn, blocks_local, x_micro,
                                    axis_name, n_stages, n_micro,
                                    remat=remat, fp32_comm=fp32_comm)
            losses = jax.vmap(
                lambda h, l: head_loss_fn(head_params, h, l))(outputs,
                                                              lab_micro)
            loss = jnp.mean(losses)
            loss = last_stage_value(loss, axis_name, n_stages)
            if dp_active:
                loss = jax.lax.pmean(loss, data_axis)
            return loss

        if blocks_specs is None:
            bspecs = jax.tree_util.tree_map(
                lambda _: P(axis_name), params["blocks"])
        else:
            bspecs = blocks_specs
        other = P()
        especs = embed_specs if embed_specs is not None else \
            jax.tree_util.tree_map(lambda _: other, params["embed"])
        hspecs = head_specs if head_specs is not None else \
            jax.tree_util.tree_map(lambda _: other, params["head"])
        batch_spec = P(data_axis) if dp_active else P()
        mapped = shard_map(
            inner, mesh=mesh,
            in_specs=(bspecs, especs, hspecs, batch_spec, batch_spec),
            out_specs=other,
            check_vma=False)
        return mapped(params["blocks"], params["embed"], params["head"],
                      tokens, labels)

    return loss_fn


def module_pipeline_loss_fn(module, mesh, n_micro, axis_name=PIPE_AXIS,
                            data_axis=None, fp32_comm=None, remat=True):
    """Lower an arbitrary `PipelineModule` (heterogeneous LayerSpec list)
    onto the SPMD ppermute executor (reference `pipe/engine.py:654-1139`
    executes any layer list across stages; here the whole 1F1B batch is
    one shard_map program over the ``pipe`` mesh axis).

    SPMD needs every stage to run the same program with uniform shapes,
    but heterogeneous stages have different activation shapes and param
    structures. Both are made uniform by FLATTENING:

    - inter-stage activations travel as one padded flat buffer sized to
      the largest boundary activation; each stage's `lax.switch` branch
      reshapes its statically-known input shape out of the buffer and
      flattens its output back in;
    - per-stage params are packed into a [n_stages, P_max] row matrix
      sharded over ``pipe`` (each stage materializes only its row — the
      reference's "build only local layers", `module.py:358`); branches
      unpack their row into the layer subtrees.

    Tied subtrees stay replicated over ``pipe`` and their gradient psum
    falls out of the shard_map transpose — the reference's
    `allreduce_tied_weight_gradients`.

    Returns ``loss_fn(params, batch, rng)`` over the FULL effective batch
    (the batch splits into `n_micro` pipeline micro-batches internally).

    Caveat: during pipeline fill/drain, stages run on zero buffers whose
    results are discarded by select (never blended into outputs). Layer
    primals may be non-finite on zeros without harm, but their VJPs
    should not emit NaN under a zero cotangent (0·∞ patterns, e.g.
    unguarded ``x/|x|``) — the same discipline `jnp.where` gradients
    require everywhere in JAX.
    """
    from ..runtime.pipe import p2p

    n_stages = int(mesh.shape[axis_name])
    if module.num_stages != n_stages:
        raise ValueError(
            f"module has {module.num_stages} stages but mesh axis "
            f"{axis_name!r} has {n_stages}")
    parts = module.parts
    dp_active = (data_axis is not None and data_axis in mesh.axis_names
                 and int(mesh.shape[data_axis]) > 1)

    def stage_param_leaves(params, s):
        """Non-tied leaves of stage s, in deterministic order."""
        leaves = []
        for idx in range(parts[s], parts[s + 1]):
            if module._tied_keys_per_layer[idx] is None:
                leaves.extend(
                    jax.tree_util.tree_leaves(params["layers"][idx]))
        return leaves

    def loss_fn(params, batch, rng=None):
        inputs, labels = batch
        b = inputs.shape[0]
        if b % n_micro != 0:
            raise ValueError(
                f"batch {b} must split into n_micro={n_micro}")
        mb = b // n_micro
        in_micro = inputs.reshape((n_micro, mb) + inputs.shape[1:])
        lab_micro = labels.reshape((n_micro, mb) + labels.shape[1:])

        # --- static per-stage activation shapes (per-dp-shard sizes) ----
        dp_size = int(mesh.shape[data_axis]) if dp_active else 1
        if mb % dp_size != 0:
            raise ValueError(
                f"micro-batch {mb} must divide over data axis {dp_size}")
        mb_local = mb // dp_size
        stage_in, stage_out = [], []
        cur = jax.ShapeDtypeStruct((mb_local,) + inputs.shape[1:],
                                   inputs.dtype)
        for s in range(n_stages):
            stage_in.append(cur)
            cur = jax.eval_shape(
                lambda p, xx, s=s: module.forward_range(
                    p, xx, parts[s], parts[s + 1]), params, cur)
            stage_out.append(cur)
        act_dtype = stage_in[0].dtype
        for sd in stage_in + stage_out:
            if sd.dtype != act_dtype:
                raise ValueError(
                    "pipelined stages must share one activation dtype; "
                    f"got {sd.dtype} vs {act_dtype}")

        def numel(sd):
            return int(np.prod(sd.shape))

        A = max(numel(sd) for sd in stage_in + stage_out)

        # --- pack per-stage params into [n_stages, P_max] ----------------
        leaves_by_stage = [stage_param_leaves(params, s)
                           for s in range(n_stages)]
        sizes = [sum(int(np.prod(l.shape)) for l in ls)
                 for ls in leaves_by_stage]
        p_dtypes = {l.dtype for ls in leaves_by_stage for l in ls}
        if len(p_dtypes) > 1:
            raise ValueError(
                f"pipelined stage params must share one dtype; {p_dtypes}")
        p_dtype = p_dtypes.pop() if p_dtypes else jnp.float32
        P_max = max(max(sizes), 1)
        rows = []
        for ls, sz in zip(leaves_by_stage, sizes):
            flat = (jnp.concatenate([jnp.ravel(l) for l in ls])
                    if ls else jnp.zeros((0,), p_dtype))
            rows.append(jnp.pad(flat, (0, P_max - sz)))
        packed = jax.lax.with_sharding_constraint(
            jnp.stack(rows),
            jax.sharding.NamedSharding(mesh, P(axis_name, None)))

        tied = params["tied"]

        # --- per-stage branch: flat buf -> flat buf ----------------------
        def make_branch(s):
            in_sd, out_sd = stage_in[s], stage_out[s]

            def branch(row, tied, buf, mb_rng):
                x = buf[:numel(in_sd)].reshape(in_sd.shape)
                # rebuild this stage's layer params from the flat row
                layers = [{} for _ in range(len(module.layers))]
                off = 0
                for idx in range(parts[s], parts[s + 1]):
                    if module._tied_keys_per_layer[idx] is not None:
                        continue
                    tmpl = params["layers"][idx]
                    lvs, tdef = jax.tree_util.tree_flatten(tmpl)
                    rebuilt = []
                    for l in lvs:
                        n = int(np.prod(l.shape))
                        rebuilt.append(
                            row[off:off + n].reshape(l.shape))
                        off += n
                    layers[idx] = jax.tree_util.tree_unflatten(tdef,
                                                               rebuilt)
                pseudo = {"layers": layers, "tied": tied}
                y = module.forward_range(pseudo, x, parts[s],
                                         parts[s + 1], rng=mb_rng)
                return jnp.pad(jnp.ravel(y), (0, A - numel(out_sd)))

            return branch

        branches = [make_branch(s) for s in range(n_stages)]

        # --- shard_map body: fill/steady/drain scan ----------------------
        def inner(packed_local, tied, in_micro, lab_micro, rng):
            stage = jax.lax.axis_index(axis_name)
            row = packed_local[0]

            def apply_stage(buf, mb_rng):
                fns = [(lambda b, r, s=s: branches[s](row, tied, b, r))
                       for s in range(n_stages)]
                return jax.lax.switch(stage, fns, buf, mb_rng)

            body = jax.checkpoint(apply_stage) if remat else apply_stage

            flat_in = jax.vmap(
                lambda x: jnp.pad(jnp.ravel(x).astype(act_dtype),
                                  (0, A - numel(stage_in[0]))))(in_micro)

            total_ticks = n_micro + n_stages - 1

            def tick(carry, t):
                buf, outputs = carry
                idx = jnp.clip(t, 0, n_micro - 1)
                inject = jax.lax.dynamic_index_in_dim(flat_in, idx, 0,
                                                      keepdims=False)
                x = jnp.where(stage == 0, inject, buf)
                # per-micro-batch stream (layer-level fold_in happens in
                # forward_range); stochastic layers get distinct keys per
                # micro-batch, like the sequential gas scan. The micro in
                # flight at THIS stage at tick t is t - stage (stage 0's
                # index `idx` would make drain ticks reuse late micros'
                # keys downstream).
                mb_idx = jnp.clip(t - stage, 0, n_micro - 1)
                y = body(x, jax.random.fold_in(rng, mb_idx))
                out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
                # select (NaN-safe), not a blend — see spmd_pipeline
                write = t >= n_stages - 1
                current = jax.lax.dynamic_index_in_dim(outputs, out_idx,
                                                       0, keepdims=False)
                outputs = jax.lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(write, y, current), out_idx, 0)
                buf_next = p2p.send_to_next(y, axis_name, n_stages,
                                            fp32_comm=fp32_comm)
                return (buf_next, outputs), None

            buf0 = jnp.zeros((A,), act_dtype)
            outputs0 = jnp.zeros((n_micro, A), act_dtype)
            (_, outputs), _ = jax.lax.scan(tick, (buf0, outputs0),
                                           jnp.arange(total_ticks))

            out_sd = stage_out[-1]
            outs = outputs[:, :numel(out_sd)].reshape(
                (n_micro,) + out_sd.shape)
            if module.loss_fn is not None:
                losses = jax.vmap(module.loss_fn)(outs, lab_micro)
            else:
                losses = jnp.mean(outs, axis=tuple(range(1, outs.ndim)))
            loss = jnp.mean(losses)
            loss = last_stage_value(loss, axis_name, n_stages)
            if dp_active:
                loss = jax.lax.pmean(loss, data_axis)
            return loss

        tied_specs = jax.tree_util.tree_map(lambda _: P(), tied)
        # micro dim 0 is a scan axis; data parallelism shards dim 1
        batch_spec = P(None, data_axis) if dp_active else P()
        if rng is None:
            rng = jax.random.PRNGKey(0)
        mapped = shard_map(
            inner, mesh=mesh,
            in_specs=(P(axis_name, None), tied_specs, batch_spec,
                      batch_spec, P()),
            out_specs=P(),
            check_vma=False)
        return mapped(packed, tied, in_micro, lab_micro, rng)

    return loss_fn


class GPTNeoXPipeSPMD:
    """Flagship model wired through the SPMD pipeline executor.

    Engine-protocol object (loss_fn / init_params / param_specs): blocks
    are stacked [L, ...] and sharded over ``pipe``; embed/head replicated
    over ``pipe`` and tensor-sharded over ``model`` when present.
    """

    def __init__(self, config, mesh, n_micro, remat=True, fp32_comm=None,
                 use_pallas=True):
        from ..models import gpt_neox as M
        from .mesh import DATA_AXIS, MODEL_AXIS
        self.cfg = config
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_stages = int(mesh.shape[PIPE_AXIS])
        self.mp = int(mesh.shape[MODEL_AXIS]) \
            if MODEL_AXIS in mesh.axis_names else 1
        if config.num_layers % self.n_stages != 0:
            raise ValueError(
                f"num_layers {config.num_layers} must divide evenly over "
                f"{self.n_stages} pipeline stages")
        if self.mp > 1:
            for name, dim in (("num_heads", config.num_heads),
                              ("hidden_size", config.hidden_size),
                              ("intermediate_size",
                               config.intermediate_size)):
                if dim % self.mp != 0:
                    raise ValueError(
                        f"{name} {dim} must divide over model-parallel "
                        f"size {self.mp}")
        self._M = M

        cos_sin = M._rotary_cache(config, config.max_seq_len)
        mp = self.mp

        def stage_fn(blocks_local, x):
            # scan over this stage's layers (leading dim of each leaf).
            def one(x, bp):
                cs = (cos_sin[0][:x.shape[1]], cos_sin[1][:x.shape[1]],
                      cos_sin[2])
                if mp > 1:
                    return M.block_forward_tp(config, bp, x, cs,
                                              MODEL_AXIS, mp,
                                              use_pallas=use_pallas), None
                return M.block_forward(config, bp, x, cs,
                                       use_pallas=use_pallas), None

            y, _ = jax.lax.scan(one, x, blocks_local)
            return y

        if mp > 1 and config.vocab_size % mp != 0:
            raise ValueError(
                f"vocab_size {config.vocab_size} must divide over "
                f"model-parallel size {mp}")

        def embed_fn(embed_params, tokens):
            wte = embed_params["wte"]
            if mp == 1:
                return wte[tokens]
            # Megatron VocabParallelEmbedding: each model rank holds a
            # contiguous vocab slice; out-of-range tokens contribute
            # zero, psum assembles the full embedding.
            v_local = wte.shape[0]
            start = jax.lax.axis_index(MODEL_AXIS) * v_local
            offset = tokens - start
            in_range = (offset >= 0) & (offset < v_local)
            safe = jnp.clip(offset, 0, v_local - 1)
            x = wte[safe] * in_range[..., None].astype(wte.dtype)
            return jax.lax.psum(x, MODEL_AXIS)

        def head_loss_fn(head_params, hidden, labels):
            h = M.layer_norm(hidden, head_params["final_ln"]["scale"],
                             head_params["final_ln"]["bias"],
                             config.layernorm_eps)
            wte = head_params["wte"]
            logits = jnp.einsum(
                "bsh,vh->bsv", h, wte.astype(h.dtype),
                preferred_element_type=jnp.float32)
            if mp == 1:
                return M.lm_loss(logits, labels)
            # Megatron vocab-parallel cross entropy: the [*, V/mp] logits
            # shard never leaves its rank — softmax stats and the target
            # logit travel as two scalars-per-token psums.
            logits = logits[:, :-1, :]
            targets = labels[:, 1:]
            v_local = wte.shape[0]
            start = jax.lax.axis_index(MODEL_AXIS) * v_local
            # the max shift is a pure stabilizer (lse is invariant to it),
            # so stop_gradient is exact; the cross-rank max goes through
            # all_gather because pmax has no differentiation rule
            local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
            m = jnp.max(jax.lax.all_gather(local_max, MODEL_AXIS), axis=0)
            z = jax.lax.psum(
                jnp.sum(jnp.exp(logits - m[..., None]), axis=-1),
                MODEL_AXIS)
            lse = jnp.log(z) + m
            valid = targets != -100
            offset = jnp.where(valid, targets, 0) - start
            in_range = (offset >= 0) & (offset < v_local)
            safe = jnp.clip(offset, 0, v_local - 1)
            picked_local = jnp.take_along_axis(
                logits, safe[..., None], axis=-1).squeeze(-1)
            picked = jax.lax.psum(
                picked_local * in_range.astype(jnp.float32), MODEL_AXIS)
            nll = (lse - picked) * valid
            return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)

        # One spec tree shared by the shard_map in_specs and the engine's
        # GSPMD placement (param_specs) so they can never drift.
        if mp > 1:
            self._tp_specs = {
                "embed": {"wte": P(MODEL_AXIS, None)},   # vocab-sharded
                "blocks": M.block_param_specs_tp(pipe_axis=PIPE_AXIS),
                "head": {"final_ln": {"scale": P(), "bias": P()},
                         "wte": P(MODEL_AXIS, None)},
            }
        else:
            self._tp_specs = None
        self.loss_fn = pipeline_loss_fn(
            embed_fn, stage_fn, head_loss_fn, mesh, n_micro, remat=remat,
            fp32_comm=fp32_comm, data_axis=DATA_AXIS,
            blocks_specs=self._tp_specs["blocks"] if mp > 1 else None,
            embed_specs=self._tp_specs["embed"] if mp > 1 else None,
            head_specs=self._tp_specs["head"] if mp > 1 else None)

    def init_params(self, rng):
        M, cfg = self._M, self.cfg
        keys = jax.random.split(rng, cfg.num_layers + 2)
        blocks = [M.init_block_params(cfg, keys[i + 1])
                  for i in range(cfg.num_layers)]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *blocks)
        return {
            "embed": {"wte": M._dense_init(keys[0], (cfg.vocab_size,
                                                     cfg.hidden_size),
                                           cfg.param_dtype)},
            "blocks": stacked,
            "head": {
                "final_ln": {
                    "scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype),
                    "bias": jnp.zeros((cfg.hidden_size,), cfg.param_dtype),
                },
                "wte": M._dense_init(keys[-1], (cfg.vocab_size,
                                                cfg.hidden_size),
                                     cfg.param_dtype),
            },
        }

    def param_specs(self, params, mesh):
        if self.mp > 1:
            return self._tp_specs

        def blocks_spec(leaf):
            return P(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return {
            "embed": jax.tree_util.tree_map(lambda _: P(),
                                            params["embed"]),
            "blocks": jax.tree_util.tree_map(blocks_spec,
                                             params["blocks"]),
            "head": jax.tree_util.tree_map(lambda _: P(), params["head"]),
        }
