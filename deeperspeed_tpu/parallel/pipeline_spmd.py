"""Compiled SPMD pipeline executor.

This is the TPU lowering of the reference's pipeline engine
(`deepspeed/runtime/pipe/engine.py` + `schedule.py`): instead of a host
loop interpreting Send/Recv/Forward/Backward instructions per stage, the
whole schedule becomes ONE jitted program under `shard_map` over the
``pipe`` mesh axis:

- every stage runs the same program on its shard of a stacked layer
  parameter pytree (leaves [L, ...] sharded over ``pipe`` on dim 0);
- micro-batches flow stage-to-stage via `ppermute` (XLA
  collective-permute riding ICI/DCN);
- the fill/steady/drain structure is a `lax.scan` over
  ``n_micro + n_stages - 1`` ticks (GPipe-style; differentiating through
  the scan yields the reverse-order backward schedule automatically, with
  `jax.checkpoint` on the stage body bounding activation memory);
- loss is computed by the last stage and broadcast with a masked psum —
  the analogue of `_aggregate_total_loss` (`pipe/engine.py:559`).

Use `pipeline_loss_fn` to build an engine-compatible loss from (embed_fn,
stage_fn, head_fn) triples; `GPTNeoXPipeSPMD` wires it for the flagship
model.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from ..compat import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import PIPE_AXIS


def spmd_pipeline(stage_fn, stage_params, x_micro, axis_name, n_stages,
                  n_micro, remat=True, fp32_comm=None):
    """Run the pipeline body inside shard_map.

    Args:
      stage_fn: (stage_params, x) -> y; this stage's layer stack.
      stage_params: pytree whose leaves lead with the local layer dim.
      x_micro: [M, mb, ...] micro-batched stage-0 inputs (replicated).
      fp32_comm: upcast bf16/fp16 activations to fp32 for the inter-stage
        wire (fork feature, reference `pipe/p2p.py:31-62`); the backward
        ppermute of the transposed program inherits the same precision.
        None (default) defers to `p2p.configure(...)`'s module setting —
        which `PipelineEngine.__init__` sets from the `fp32_allreduce`
        config before the first compile.
    Returns [M, mb, ...] outputs, valid on the LAST stage (others carry
    bubble garbage — mask downstream).
    """
    from ..runtime.pipe import p2p

    stage = jax.lax.axis_index(axis_name)
    total_ticks = n_micro + n_stages - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def tick(carry, t):
        buf, outputs = carry
        idx = jnp.clip(t, 0, n_micro - 1)
        inject = jax.lax.dynamic_index_in_dim(x_micro, idx, 0,
                                              keepdims=False)
        x = jnp.where(stage == 0, inject.astype(buf.dtype), buf)
        y = body(stage_params, x)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        # select, NOT an arithmetic blend: fill-tick computations run on
        # garbage buffers and may be NaN/Inf, which a blend would
        # propagate into the real outputs (0*NaN = NaN)
        write = t >= n_stages - 1
        current = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                               keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, current), out_idx, 0)
        buf_next = p2p.send_to_next(y, axis_name, n_stages,
                                    fp32_comm=fp32_comm)
        return (buf_next, outputs), None

    mb_shape = x_micro.shape[1:]
    y_shape = jax.eval_shape(
        lambda p, x: stage_fn(p, x), stage_params,
        jax.ShapeDtypeStruct(mb_shape, x_micro.dtype))
    buf0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    outputs0 = jnp.zeros((n_micro,) + y_shape.shape, y_shape.dtype)

    (_, outputs), _ = jax.lax.scan(tick, (buf0, outputs0),
                                   jnp.arange(total_ticks))
    return outputs


def _fp32_scaled(grads, scale):
    """fp32 view of a grad tree, optionally loss-scale multiplied (the
    engine fast path's epilogue, shared by both loss-fn builders)."""
    grads = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), grads)
    if scale is not None:
        s32 = jnp.asarray(scale, jnp.float32)
        grads = jax.tree_util.tree_map(lambda g: g * s32, grads)
    return grads


def last_stage_value(value, axis_name, n_stages):
    """Broadcast a last-stage scalar/array to every stage (masked psum)."""
    stage = jax.lax.axis_index(axis_name)
    masked = jnp.where(stage == n_stages - 1, value,
                       jnp.zeros_like(value))
    return jax.lax.psum(masked, axis_name)


# ---------------------------------------------------------------------------
# 1F1B executors (manual pipeline autodiff at stage granularity)
#
# The GPipe-shaped scan above differentiates THROUGH the scan, so autodiff
# saves per-tick residuals and live activation memory grows with n_micro.
# The two functions below realize the reference's 1F1B memory bound
# (`schedule.py:243-249`: live buffers ~ n_stages, not n_micro) in one
# compiled program: the backward schedule is hand-interleaved into the
# same tick loop, per-stage VJPs are taken explicitly (recompute-from-
# stashed-input — remat by construction), and nothing differentiates
# through the scan at all.
#
# Both run INSIDE shard_map over the pipe axis. The schedule is the two
# clock relations of `runtime/pipe/schedule.py`: forward of micro m on
# stage s at half-tick t = s + 2m, backward at t = 2S - 1 - s + 2m.
# Adjacent stages therefore alternate parity, and each tick sends one
# activation down and one input-cotangent up (one of the two is bubble
# garbage, gated by the receiver's validity mask).
# ---------------------------------------------------------------------------


def pipeline_1f1b_ticks(stage_apply, diff_args, buf_template, n_stages,
                        n_micro, axis_name, rng, fp32_comm=None,
                        wire_latency=1):
    """Interleaved forward+backward 1F1B loop; returns (loss, grads).

    ``wire_latency=2`` dispatches to the software-pipelined executor
    (`parallel.schedule.pipeline_1f1b_overlapped_ticks`): each tick
    issues the PREVIOUS tick's ppermutes before its compute, hiding the
    p2p transfers behind the stage matmuls at the cost of doubled
    fill/drain (the ``pipeline.comm_overlap`` knob).

    Args (inside shard_map over `axis_name`):
      stage_apply: (diff_args, buf, m_idx, rng) -> (out_buf, loss_f32).
        Encapsulates per-stage behavior: stage 0 ignores `buf` and
        injects micro m's input; the last stage computes the per-micro
        loss (other stages return 0.0). `out_buf` must match
        `buf_template`.
      diff_args: pytree of parameters to differentiate against.
      buf_template: ShapeDtypeStruct of the inter-stage activation buffer.
      rng: base key; stage_apply derives per-micro keys (the SAME key is
        used to recompute micro m's forward in its backward tick).
    Returns:
      loss: mean over micro-batches (valid on the last stage only —
        broadcast with `last_stage_value`).
      grads: pytree like diff_args (fp32), this device's local
        contribution; the caller reduces over replicated axes.

    Live activation state: a [D, |buf|] stash with D = min(n_stages,
    n_micro) — micro m's stage input is stashed at its forward tick and
    recomputed through `jax.vjp` at its backward tick, so peak memory is
    bounded by pipeline depth, not micro-batch count.
    """
    from ..runtime.pipe import p2p

    if int(wire_latency) == 2:
        from .schedule import pipeline_1f1b_overlapped_ticks
        return pipeline_1f1b_overlapped_ticks(
            stage_apply, diff_args, buf_template, n_stages, n_micro,
            axis_name, rng, fp32_comm=fp32_comm)
    if int(wire_latency) != 1:
        raise ValueError(f"wire_latency must be 1 or 2, got "
                         f"{wire_latency}")

    stage = jax.lax.axis_index(axis_name)
    D = min(n_stages, n_micro)
    total = 2 * (n_micro + n_stages - 1)
    buf0 = jnp.zeros(buf_template.shape, buf_template.dtype)

    gacc0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), diff_args)

    def tick(carry, t):
        fwd_buf, bwd_buf, stash, gacc, loss_acc = carry
        tf = t - stage                       # forward clock
        tb = t - (2 * n_stages - 1 - stage)  # backward clock
        is_fwd = (tf % 2) == 0
        m_f = jnp.clip(tf // 2, 0, n_micro - 1)
        valid_f = is_fwd & (tf >= 0) & (tf < 2 * n_micro)
        m_b = jnp.clip(tb // 2, 0, n_micro - 1)
        valid_b = jnp.logical_not(is_fwd) & (tb >= 0) & (tb < 2 * n_micro)

        def fwd_tick(fwd_buf, bwd_buf, stash, gacc):
            y, l = stage_apply(diff_args, fwd_buf, m_f, rng)
            # Gated stash write: drain ticks carry stale buffers whose
            # clipped slot would clobber a still-live micro's input.
            slot = m_f % D
            keep = jax.lax.dynamic_index_in_dim(stash, slot, 0,
                                                keepdims=False)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(valid_f, fwd_buf, keep), slot, 0)
            return y, buf0, l.astype(jnp.float32), stash, gacc

        def bwd_tick(fwd_buf, bwd_buf, stash, gacc):
            x = jax.lax.dynamic_index_in_dim(stash, m_b % D, 0,
                                             keepdims=False)
            # Last stage seeds from its own loss; everyone else pulls
            # back the downstream cotangent.
            cot_y = jnp.where(stage == n_stages - 1,
                              jnp.zeros_like(bwd_buf), bwd_buf)
            cot_l = jnp.asarray(1.0 / n_micro, jnp.float32)
            _, pull = jax.vjp(
                lambda args, xx: stage_apply(args, xx, m_b, rng),
                diff_args, x)
            args_bar, x_bar = pull((cot_y.astype(buf_template.dtype),
                                    cot_l))
            gacc = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(valid_b,
                                           g.astype(jnp.float32), 0.0),
                gacc, args_bar)
            return buf0, x_bar, jnp.asarray(0.0, jnp.float32), stash, gacc

        y_out, xbar_out, l, stash, gacc = jax.lax.cond(
            is_fwd, fwd_tick, bwd_tick, fwd_buf, bwd_buf, stash, gacc)
        loss_acc = loss_acc + jnp.where(
            valid_f & (stage == n_stages - 1), l, 0.0)
        # Neighbor exchange: activations down, input cotangents up —
        # gated by PHASE. Within the steady state each stage's payload
        # on one of the two wires is garbage every tick (the half-tick
        # parity), but that garbage is interleaved per-stage so the
        # collective must still run; in the BUBBLE phases the whole wire
        # is dead uniformly across stages (down-wire after the last
        # useful activation send, up-wire before the first backward
        # exists / after the last), so the cond predicate is replicated
        # and the ppermute is skipped at runtime — ~2x boundary
        # bandwidth saved during fill/drain (round-4 VERDICT Weak #5).
        # Useful down-sends: stage s's forward of micro m at t = s + 2m,
        # consumed by s+1 next tick → live for t <= (S-2) + 2(M-1).
        # Useful up-sends: stage s's backward at t = 2S-1-s + 2m from
        # s >= 1 → live for 2S-1-(S-1) = S <= t <= 2S-2 + 2(M-1).
        down_live = t <= n_stages + 2 * n_micro - 4
        up_live = (t >= n_stages) & (t <= 2 * n_stages + 2 * n_micro - 4)
        fwd_next = jax.lax.cond(
            down_live,
            lambda y: p2p.send_to_next(y, axis_name, n_stages,
                                       fp32_comm=fp32_comm),
            lambda y: jnp.zeros_like(y), y_out)
        bwd_next = jax.lax.cond(
            up_live,
            lambda x: p2p.send_to_prev(x, axis_name, n_stages,
                                       fp32_comm=fp32_comm),
            lambda x: jnp.zeros_like(x), xbar_out)
        return (fwd_next, bwd_next, stash, gacc, loss_acc), None

    stash0 = jnp.zeros((D,) + buf_template.shape, buf_template.dtype)
    carry0 = (buf0, buf0, stash0, gacc0, jnp.asarray(0.0, jnp.float32))
    (_, _, _, gacc, loss_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(total))
    return loss_acc / n_micro, gacc


def pipeline_forward_ticks(stage_apply, diff_args, buf_template, n_stages,
                           n_micro, axis_name, rng, fp32_comm=None,
                           collect_outputs=False):
    """Forward-only fill/drain loop (eval/inference): full ticks, no
    stash, no grads. Returns (loss, outputs | None); loss is the mean
    over micro-batches (valid on the last stage), `outputs` is the last
    stage's [n_micro, *buf] boundary outputs when requested."""
    from ..runtime.pipe import p2p

    stage = jax.lax.axis_index(axis_name)
    total = n_micro + n_stages - 1
    buf0 = jnp.zeros(buf_template.shape, buf_template.dtype)

    def tick(carry, t):
        buf, loss_acc, outputs = carry
        m = jnp.clip(t - stage, 0, n_micro - 1)
        valid = (t >= stage) & (t - stage < n_micro)
        y, l = stage_apply(diff_args, buf, m, rng)
        loss_acc = loss_acc + jnp.where(
            valid & (stage == n_stages - 1), l.astype(jnp.float32), 0.0)
        if outputs is not None:
            cur = jax.lax.dynamic_index_in_dim(outputs, m, 0,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, cur), m, 0)
        buf = p2p.send_to_next(y, axis_name, n_stages,
                               fp32_comm=fp32_comm)
        return (buf, loss_acc, outputs), None

    outputs0 = jnp.zeros((n_micro,) + buf_template.shape,
                         buf_template.dtype) if collect_outputs else None
    carry0 = (buf0, jnp.asarray(0.0, jnp.float32), outputs0)
    (_, loss_acc, outputs), _ = jax.lax.scan(tick, carry0,
                                             jnp.arange(total))
    return loss_acc / n_micro, outputs


def pipeline_loss_fn(embed_fn, stage_fn, head_loss_fn, mesh, n_micro,
                     axis_name=PIPE_AXIS, remat=True, fp32_comm=None,
                     data_axis=None, blocks_specs=None, embed_specs=None,
                     head_specs=None, wire_latency=1):
    """Build loss(params, batch, rng) running the block stack pipelined.

    params = {"embed": ..., "blocks": stacked leaves [L, ...],
              "head": ...}; blocks are sharded over (axis_name,) on dim 0
    — or per `blocks_specs` (a matching pytree of PartitionSpecs, e.g.
    `block_param_specs_tp` for tensor-parallel slices). batch =
    (tokens [B, S], labels). The global batch splits into `n_micro`
    micro-batches along dim 0.

    The returned function is a `jax.custom_vjp`: called directly it runs
    a forward-only fill/drain loop; under `value_and_grad` it runs the
    hand-interleaved 1F1B loop (`pipeline_1f1b_ticks`), so live
    activation memory is bounded by min(n_stages, n_micro) boundary
    buffers, not n_micro. Stage-edge work is gated per device with
    `lax.cond`: only stage 0 embeds, only the last stage runs the
    LM-head loss — interior stages skip both entirely. `remat` is
    accepted for API compatibility but ignored: the 1F1B backward
    recomputes each stage from its stashed input by construction.

    With `data_axis` set (and present in the mesh), the batch is consumed
    sharded over that axis and the loss is the data-parallel mean — a
    full dp x pp (x tp) step in one program. Gradients are reduced
    explicitly: for each param leaf, psum over every mesh axis its
    PartitionSpec does not use (tp-replicated leaves, pipe-replicated
    embed/head) and pmean over the data axis.
    """
    n_stages = int(mesh.shape[axis_name])
    dp_active = (data_axis is not None and data_axis in mesh.axis_names
                 and int(mesh.shape[data_axis]) > 1)

    def _axes_used(spec):
        used = set()
        for part in spec:
            if part is None:
                continue
            if isinstance(part, tuple):
                used.update(part)
            else:
                used.add(part)
        return used

    def _reduce_grads(gtree, spec_tree):
        """psum a leaf over every mesh axis absent from its spec (the
        computation was replicated there), pmean over data (the loss is
        the dp mean)."""
        def red(g, spec):
            used = _axes_used(spec)
            for axis in mesh.axis_names:
                if axis in used or int(mesh.shape[axis]) == 1:
                    continue
                g = (jax.lax.pmean(g, axis) if axis == data_axis
                     else jax.lax.psum(g, axis))
            return g
        return jax.tree_util.tree_map(
            red, gtree, spec_tree,
            is_leaf=lambda x: isinstance(x, P))

    def _specs(params):
        bspecs = blocks_specs if blocks_specs is not None else \
            jax.tree_util.tree_map(lambda _: P(axis_name),
                                   params["blocks"])
        especs = embed_specs if embed_specs is not None else \
            jax.tree_util.tree_map(lambda _: P(), params["embed"])
        hspecs = head_specs if head_specs is not None else \
            jax.tree_util.tree_map(lambda _: P(), params["head"])
        return bspecs, especs, hspecs

    def _call(params, batch, rng, mode):
        tokens, labels = batch
        bspecs, especs, hspecs = _specs(params)
        batch_spec = P(data_axis) if dp_active else P()
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def inner(blocks_local, embed_params, head_params, tokens,
                  labels, rng):
            stage = jax.lax.axis_index(axis_name)
            b = tokens.shape[0]
            if b % n_micro != 0 or b < n_micro:
                raise ValueError(
                    f"per-data-rank batch {b} must split into n_micro="
                    f"{n_micro} micro-batches (global batch / dp size "
                    f"must be a multiple of n_micro)")
            mb = b // n_micro
            tok_micro = tokens.reshape((n_micro, mb) + tokens.shape[1:])
            lab_micro = labels.reshape((n_micro, mb) + labels.shape[1:])
            buf_tmpl = jax.eval_shape(
                embed_fn, embed_params,
                jax.ShapeDtypeStruct((mb,) + tokens.shape[1:],
                                     tokens.dtype))

            def stage_apply(args, buf, m_idx, rng_):
                blocks, embed, head = args
                tok = jax.lax.dynamic_index_in_dim(tok_micro, m_idx, 0,
                                                   keepdims=False)
                # only stage 0 pays the embedding lookup
                x = jax.lax.cond(
                    stage == 0,
                    lambda: embed_fn(embed, tok).astype(buf.dtype),
                    lambda: buf)
                y = stage_fn(blocks, x)
                lab = jax.lax.dynamic_index_in_dim(lab_micro, m_idx, 0,
                                                   keepdims=False)
                # only the last stage pays the LM-head matmul + loss
                l = jax.lax.cond(
                    stage == n_stages - 1,
                    lambda: head_loss_fn(head, y, lab).astype(
                        jnp.float32),
                    lambda: jnp.asarray(0.0, jnp.float32))
                return y, l

            diff_args = (blocks_local, embed_params, head_params)
            if mode == "grad":
                loss, gacc = pipeline_1f1b_ticks(
                    stage_apply, diff_args, buf_tmpl, n_stages, n_micro,
                    axis_name, rng, fp32_comm=fp32_comm,
                    wire_latency=wire_latency)
                loss = last_stage_value(loss, axis_name, n_stages)
                if dp_active:
                    loss = jax.lax.pmean(loss, data_axis)
                gb, ge, gh = gacc
                gb = _reduce_grads(gb, bspecs)
                ge = _reduce_grads(ge, especs)
                gh = _reduce_grads(gh, hspecs)
                return loss, gb, ge, gh

            loss, _ = pipeline_forward_ticks(
                stage_apply, diff_args, buf_tmpl, n_stages, n_micro,
                axis_name, rng, fp32_comm=fp32_comm)
            loss = last_stage_value(loss, axis_name, n_stages)
            if dp_active:
                loss = jax.lax.pmean(loss, data_axis)
            return loss

        out_specs = (P(), bspecs, especs, hspecs) if mode == "grad" \
            else P()
        mapped = shard_map(
            inner, mesh=mesh,
            in_specs=(bspecs, especs, hspecs, batch_spec, batch_spec,
                      P()),
            out_specs=out_specs,
            check_vma=False)
        return mapped(params["blocks"], params["embed"], params["head"],
                      tokens, labels, rng)

    def primal(params, batch, rng=None):
        return _call(params, batch, rng, "fwd")

    def _run_grad(params, batch, rng):
        loss, gb, ge, gh = _call(params, batch, rng, "grad")
        return loss, {"blocks": gb, "embed": ge, "head": gh}

    def fwd_rule(params, batch, rng=None):
        loss, grads = _run_grad(params, batch, rng)
        return loss, (grads, params, batch, rng)

    def bwd_rule(res, cot):
        grads, params, batch, rng = res
        cot32 = cot.astype(jnp.float32)
        # custom_vjp cotangents MUST match the primal param dtypes, so
        # under bf16 this path rounds the fp32 tick-loop accumulation to
        # bf16; the engine avoids the round-trip via `loss_and_grads`.
        g = jax.tree_util.tree_map(
            lambda gg, pp: (gg.astype(jnp.float32) * cot32).astype(
                pp.dtype),
            grads, params)
        return g, _zero_tangents(batch), _zero_tangents(rng)

    loss_fn = jax.custom_vjp(primal)
    loss_fn.defvjp(fwd_rule, bwd_rule)

    def loss_and_grads(params, batch, rng=None, scale=None):
        """Engine fast path: (loss, fp32 grads) straight from the 1F1B
        fp32 accumulators — no bf16 cotangent round-trip. `scale`
        multiplies the grads in fp32 (loss-scaling)."""
        loss, grads = _run_grad(params, batch, rng)
        return loss, _fp32_scaled(grads, scale)

    loss_fn.loss_and_grads = loss_and_grads
    return loss_fn


def _zero_tangents(tree):
    """Zero cotangents for non-differentiated custom_vjp primals (int
    leaves — tokens, PRNG keys — take float0 tangents)."""
    def zt(x):
        if x is None:
            return None
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jnp.zeros_like(x)
        return np.zeros(x.shape, jax.dtypes.float0)
    return jax.tree_util.tree_map(zt, tree)


class ModulePackMeta:
    """Static packing geometry for a `PipelineModule`'s per-stage
    parameter rows — the reference's "build only local layers"
    (`pipe/module.py:186,358`) realized as a data layout: stage s's
    non-tied leaves concatenate into row s of a [n_stages, P_max] matrix
    sharded over ``pipe``, so at-rest param bytes per device scale
    1/n_stages.

    `P_max` is rounded up so the trailing dim can also shard evenly over
    the data axis (2-D pipe x data sharding of the fp32 masters/moments
    — ZeRO over the packed rows)."""

    def __init__(self, module, templates, mesh=None, axis_name=PIPE_AXIS,
                 data_axis=None):
        self.module = module
        parts = module.parts
        self.n_stages = module.num_stages
        self.stage_slots = []   # per stage: [(layer_idx, treedef, specs)]
        sizes = []
        dtypes = set()
        for s in range(self.n_stages):
            slots = []
            off = 0
            for idx in range(parts[s], parts[s + 1]):
                if module._tied_keys_per_layer[idx] is not None:
                    continue
                lvs, tdef = jax.tree_util.tree_flatten(
                    templates["layers"][idx])
                specs = []
                for l in lvs:
                    n = int(np.prod(l.shape))
                    specs.append((tuple(l.shape), jnp.dtype(l.dtype),
                                  off, n))
                    dtypes.add(jnp.dtype(l.dtype))
                    off += n
                slots.append((idx, tdef, specs))
            self.stage_slots.append(slots)
            sizes.append(off)
        if len(dtypes) > 1:
            raise ValueError(
                f"pipelined stage params must share one dtype; {dtypes}")
        self.p_dtype = dtypes.pop() if dtypes else jnp.dtype(jnp.float32)
        self.sizes = sizes
        align = 8
        if mesh is not None and data_axis is not None \
                and data_axis in mesh.axis_names:
            align = 8 * int(mesh.shape[data_axis])
        self.P_max = -(-max(max(sizes), 1) // align) * align

    def pack(self, params):
        """Natural param tree -> [n_stages, P_max] rows (in or out of
        jit). The row dtype follows the tree's leaves — the same meta
        packs compute params and their fp32 masters."""
        flats = []
        for s in range(self.n_stages):
            leaves = []
            for idx, _tdef, _specs in self.stage_slots[s]:
                leaves.extend(
                    jax.tree_util.tree_leaves(params["layers"][idx]))
            flats.append(jnp.concatenate([jnp.ravel(l) for l in leaves])
                         if leaves else None)
        dt = next((f.dtype for f in flats if f is not None), self.p_dtype)
        rows = [jnp.pad(f if f is not None else jnp.zeros((0,), dt),
                        (0, self.P_max - self.sizes[s]))
                for s, f in enumerate(flats)]
        return jnp.stack(rows)

    def pack_host(self, params, dtype=None):
        """`pack` on the host with numpy: no device allocation, so a
        host-resident tree larger than one device's HBM can be packed
        and then placed sharded (device 0 never holds the full matrix).
        `dtype` overrides the row dtype (fp32 for master trees)."""
        rows = np.zeros((self.n_stages, self.P_max),
                        np.dtype(dtype) if dtype is not None
                        else self.p_dtype)
        for s in range(self.n_stages):
            off = 0
            for idx, _tdef, _specs in self.stage_slots[s]:
                for l in jax.tree_util.tree_leaves(params["layers"][idx]):
                    a = np.asarray(l).ravel()
                    rows[s, off:off + a.size] = a
                    off += a.size
        return rows

    def unpack_stage(self, row, s):
        """One stage's [P_max] row -> the per-layer params list slot for
        `forward_range` (tied slots empty; filled from params['tied'])."""
        layers = [{} for _ in range(len(self.module.layers))]
        for idx, tdef, specs in self.stage_slots[s]:
            leaves = [row[off:off + n].reshape(shape)
                      for (shape, _dt, off, n) in specs]
            layers[idx] = jax.tree_util.tree_unflatten(tdef, leaves)
        return layers

    def unpack(self, rows, cast=True):
        """[n_stages, P_max] rows -> full per-layer params list."""
        layers = [{} for _ in range(len(self.module.layers))]
        for s in range(self.n_stages):
            row = rows[s]
            for idx, tdef, specs in self.stage_slots[s]:
                leaves = [row[off:off + n].reshape(shape).astype(dt)
                          if cast else row[off:off + n].reshape(shape)
                          for (shape, dt, off, n) in specs]
                layers[idx] = jax.tree_util.tree_unflatten(tdef, leaves)
        return layers


def module_pipeline_loss_fn(module, mesh, n_micro, axis_name=PIPE_AXIS,
                            data_axis=None, fp32_comm=None, remat=True,
                            packed_io=False, param_templates=None,
                            wire_latency=1):
    """Lower an arbitrary `PipelineModule` (heterogeneous LayerSpec list)
    onto the compiled 1F1B executor (reference `pipe/engine.py:654-1139`
    executes any layer list across stages; here the whole 1F1B batch —
    forward AND backward — is one shard_map program over the ``pipe``
    mesh axis).

    SPMD needs every stage to run the same program with uniform shapes,
    but heterogeneous stages have different activation shapes and param
    structures. Both are made uniform by FLATTENING:

    - inter-stage activations travel as one padded flat buffer sized to
      the largest boundary activation; each stage's `lax.switch` branch
      reshapes its statically-known input shape out of the buffer and
      flattens its output back in;
    - per-stage params are packed into a [n_stages, P_max] row matrix
      sharded over ``pipe`` (`ModulePackMeta`) — the reference's "build
      only local layers" (`module.py:358`).

    The returned ``loss_fn(params, batch, rng)`` is a `jax.custom_vjp`:
    called directly (eval) it runs a forward-only fill/drain loop;
    under `jax.grad`/`value_and_grad` the VJP runs `pipeline_1f1b_ticks`,
    which interleaves backward ticks into the same loop with per-stage
    recompute — live activation memory is bounded by min(n_stages,
    n_micro) boundary buffers, the reference's 1F1B cap
    (`schedule.py:243-249`), not by n_micro as in a GPipe-shaped scan.

    With ``packed_io=True`` params are the packed representation
    ``{"rows": [n_stages, P_max], "tied": {...}}`` (built once by the
    engine via `ModulePackMeta.pack`; `param_templates` supplies the
    natural shapes) — no per-call repacking appears in the step HLO and
    grads come back in the same packed layout. With the default natural
    tree IO, packing happens inside the program and grads are unpacked
    to the natural structure.

    Tied subtrees stay replicated over ``pipe``; their per-stage
    gradient contributions are psum'd over the pipe axis — the
    reference's `allreduce_tied_weight_gradients`.

    ``loss_fn.pipelined_eval(params, batch, rng, return_logits=)`` runs
    the forward-only loop and can return the last stage's outputs
    (reference `pipe/engine.py:351,422` eval/inference schedules).

    Caveat: during pipeline fill/drain, stages run on zero buffers whose
    results are discarded by select (never blended into outputs). Layer
    primals may be non-finite on zeros without harm, but their VJPs
    should not emit NaN under a zero cotangent (0*inf patterns, e.g.
    unguarded ``x/|x|``) — the same discipline `jnp.where` gradients
    require everywhere in JAX.
    """
    n_stages = int(mesh.shape[axis_name])
    if module.num_stages != n_stages:
        raise ValueError(
            f"module has {module.num_stages} stages but mesh axis "
            f"{axis_name!r} has {n_stages}")
    parts = module.parts
    dp_active = (data_axis is not None and data_axis in mesh.axis_names
                 and int(mesh.shape[data_axis]) > 1)
    if packed_io and param_templates is None:
        raise ValueError("packed_io=True requires param_templates")

    meta_box = [None]

    def get_meta(templates):
        if meta_box[0] is None:
            meta_box[0] = ModulePackMeta(module, templates, mesh=mesh,
                                         axis_name=axis_name,
                                         data_axis=data_axis)
        return meta_box[0]

    if packed_io:
        get_meta(param_templates)

    def _split(params):
        """-> (rows, tied, natural-shape templates)."""
        if packed_io:
            return params["rows"], params["tied"], param_templates
        return get_meta(params).pack(params), params["tied"], params

    def _geometry(templates, inputs):
        b = inputs.shape[0]
        if b % n_micro != 0:
            raise ValueError(
                f"batch {b} must split into n_micro={n_micro}")
        mb = b // n_micro
        dp_size = int(mesh.shape[data_axis]) if dp_active else 1
        if mb % dp_size != 0:
            raise ValueError(
                f"micro-batch {mb} must divide over data axis {dp_size}")
        mb_local = mb // dp_size
        stage_in, stage_out = [], []
        cur = jax.ShapeDtypeStruct((mb_local,) + inputs.shape[1:],
                                   inputs.dtype)
        for s in range(n_stages):
            stage_in.append(cur)
            cur = jax.eval_shape(
                lambda p, xx, s=s: module.forward_range(
                    p, xx, parts[s], parts[s + 1]), templates, cur)
            stage_out.append(cur)
        act_dtype = stage_in[0].dtype
        for sd in stage_in + stage_out:
            if sd.dtype != act_dtype:
                raise ValueError(
                    "pipelined stages must share one activation dtype; "
                    f"got {sd.dtype} vs {act_dtype}")
        A = max(int(np.prod(sd.shape)) for sd in stage_in + stage_out)
        return stage_in, stage_out, A, act_dtype, mb

    def _call(params, batch, rng, mode, collect=False, with_loss=True):
        rows, tied, templates = _split(params)
        meta = get_meta(templates)
        inputs, labels = batch
        stage_in, stage_out, A, act_dtype, mb = _geometry(templates,
                                                          inputs)
        in_micro = inputs.reshape((n_micro, mb) + inputs.shape[1:])
        lab_micro = labels.reshape((n_micro, mb) + labels.shape[1:])
        rows = jax.lax.with_sharding_constraint(
            rows, jax.sharding.NamedSharding(mesh, P(axis_name, None)))

        def numel(sd):
            return int(np.prod(sd.shape))

        out_sd = stage_out[-1]
        buf_tmpl = jax.ShapeDtypeStruct((A,), act_dtype)

        def inner(rows_local, tied, in_micro, lab_micro, rng):
            stage = jax.lax.axis_index(axis_name)

            def stage_apply(args, buf, m_idx, rng_):
                rows_l, tied_ = args
                row = rows_l[0]
                mb_rng = jax.random.fold_in(rng_, m_idx)

                def make_branch(s):
                    in_sd, o_sd = stage_in[s], stage_out[s]

                    def f(buf):
                        if s == 0:
                            x = jax.lax.dynamic_index_in_dim(
                                in_micro, m_idx, 0, keepdims=False)
                        else:
                            x = buf[:numel(in_sd)].reshape(in_sd.shape)
                        pseudo = {"layers": meta.unpack_stage(row, s),
                                  "tied": tied_}
                        y = module.forward_range(pseudo, x, parts[s],
                                                 parts[s + 1], rng=mb_rng)
                        if s == n_stages - 1:
                            if with_loss:
                                lab = jax.lax.dynamic_index_in_dim(
                                    lab_micro, m_idx, 0, keepdims=False)
                                l = (module.loss_fn(y, lab)
                                     if module.loss_fn is not None
                                     else jnp.mean(y)).astype(jnp.float32)
                            else:
                                # logits-only inference: labels untouched
                                l = jnp.asarray(0.0, jnp.float32)
                            out = (jnp.pad(
                                jnp.ravel(y).astype(act_dtype),
                                (0, A - numel(o_sd))) if collect
                                else jnp.zeros((A,), act_dtype))
                            return out, l
                        return (jnp.pad(jnp.ravel(y), (0, A - numel(o_sd))),
                                jnp.asarray(0.0, jnp.float32))

                    return f

                fns = [make_branch(s) for s in range(n_stages)]
                return jax.lax.switch(stage, fns, buf)

            diff_args = (rows_local, tied)
            if mode == "grad":
                loss, (rows_g, tied_g) = pipeline_1f1b_ticks(
                    stage_apply, diff_args, buf_tmpl, n_stages, n_micro,
                    axis_name, rng, fp32_comm=fp32_comm,
                    wire_latency=wire_latency)
                loss = last_stage_value(loss, axis_name, n_stages)
                # tied params are replicated over pipe: sum each stage's
                # contribution (reference allreduce_tied_weight_gradients)
                tied_g = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axis_name), tied_g)
                if dp_active:
                    loss = jax.lax.pmean(loss, data_axis)
                    rows_g = jax.lax.pmean(rows_g, data_axis)
                    tied_g = jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, data_axis), tied_g)
                return loss, rows_g, tied_g

            loss, outputs = pipeline_forward_ticks(
                stage_apply, diff_args, buf_tmpl, n_stages, n_micro,
                axis_name, rng, fp32_comm=fp32_comm,
                collect_outputs=collect)
            loss = last_stage_value(loss, axis_name, n_stages)
            if dp_active:
                loss = jax.lax.pmean(loss, data_axis)
            if not collect:
                return loss
            outs = outputs[:, :numel(out_sd)].reshape(
                (n_micro,) + out_sd.shape)
            if dp_active:
                outs = jnp.moveaxis(
                    jax.lax.all_gather(outs, data_axis), 0, 1)
                outs = outs.reshape((n_micro, mb) + out_sd.shape[1:])
            # NO pipe-axis psum of the [n_micro, B, S, V] outputs (the
            # largest tensor in the program — round-4 VERDICT Weak #4):
            # every stage returns its LOCAL buffer under a leading
            # pipe-sharded axis and the caller slices the last stage's
            # shard outside shard_map — a device-local read, not a
            # collective.
            return loss, outs[None]

        tied_specs = jax.tree_util.tree_map(lambda _: P(), tied)
        # micro dim 0 is a loop axis; data parallelism shards dim 1
        batch_spec = P(None, data_axis) if dp_active else P()
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if mode == "grad":
            out_specs = (P(), P(axis_name, None), tied_specs)
        elif collect:
            out_specs = (P(), P(axis_name))
        else:
            out_specs = P()
        mapped = shard_map(
            inner, mesh=mesh,
            in_specs=(P(axis_name, None), tied_specs, batch_spec,
                      batch_spec, P()),
            out_specs=out_specs,
            check_vma=False)
        # collect mode returns outs as [n_stages, n_micro, ...] SHARDED
        # over pipe — slicing the last stage inside the program would
        # make GSPMD re-insert the very broadcast this avoids; callers
        # (PipelineEngine.eval/inference_batch) read the last stage's
        # shard host-side instead.
        return mapped(rows, tied, in_micro, lab_micro, rng)

    def primal(params, batch, rng=None):
        return _call(params, batch, rng, "fwd")

    def _run_grad(params, batch, rng):
        loss, rows_g, tied_g = _call(params, batch, rng, "grad")
        if packed_io:
            grads = {"rows": rows_g, "tied": tied_g}
        else:
            grads = {"layers": get_meta(params).unpack(rows_g, cast=False),
                     "tied": tied_g}
        return loss, grads

    def fwd_rule(params, batch, rng=None):
        loss, grads = _run_grad(params, batch, rng)
        return loss, (grads, params, batch, rng)

    def bwd_rule(res, cot):
        grads, params, batch, rng = res
        cot32 = cot.astype(jnp.float32)
        # see pipeline_loss_fn.bwd_rule: the param-dtype cast is forced
        # by custom_vjp; engines use `loss_and_grads` to keep fp32
        g = jax.tree_util.tree_map(
            lambda gg, pp: (gg.astype(jnp.float32) * cot32).astype(
                pp.dtype),
            grads, params)
        return g, _zero_tangents(batch), _zero_tangents(rng)

    loss_fn = jax.custom_vjp(primal)
    loss_fn.defvjp(fwd_rule, bwd_rule)

    def loss_and_grads(params, batch, rng=None, scale=None):
        """Engine fast path: (loss, fp32 grads) with no bf16 cotangent
        round-trip (see pipeline_loss_fn.loss_and_grads)."""
        loss, grads = _run_grad(params, batch, rng)
        return loss, _fp32_scaled(grads, scale)

    loss_fn.loss_and_grads = loss_and_grads

    def pipelined_eval(params, batch, rng=None, return_logits=False,
                       with_loss=True):
        """Forward-only fill/drain across stages (reference
        InferenceSchedule, `pipe/engine.py:351,422`). With
        `return_logits` the second return value is the last stage's
        outputs under a leading [n_stages] pipe-SHARDED axis (only the
        last index is meaningful — read it host-side; no pipe-axis
        collective moves the logits). Pass ``with_loss=False`` for
        logits-only inference (labels are never read — callers may pass
        the inputs twice)."""
        if not return_logits:
            return _call(params, batch, rng, "fwd", with_loss=with_loss)
        return _call(params, batch, rng, "fwd", collect=True,
                     with_loss=with_loss)

    loss_fn.pipelined_eval = pipelined_eval
    loss_fn.pack_meta = get_meta(param_templates) if packed_io else None
    return loss_fn


class GPTNeoXPipeSPMD:
    """Flagship model wired through the SPMD pipeline executor.

    Engine-protocol object (loss_fn / init_params / param_specs): blocks
    are stacked [L, ...] and sharded over ``pipe``; embed/head replicated
    over ``pipe`` and tensor-sharded over ``model`` when present.
    """

    def __init__(self, config, mesh, n_micro, remat=True, fp32_comm=None,
                 use_pallas=True, wire_latency=1):
        from ..models import gpt_neox as M
        from .mesh import DATA_AXIS, MODEL_AXIS
        self.cfg = config
        self.config = config   # engine-protocol alias (module.config)
        self.mesh = mesh
        self.n_micro = n_micro
        self.wire_latency = int(wire_latency)
        if getattr(config, "moe_num_experts", 0):
            # see models.gpt_neox.to_layer_specs: aux loss is not
            # threaded through the stage buffers
            raise NotImplementedError(
                "MoE layers cannot be pipelined yet: the expert aux "
                "loss is not threaded through the inter-stage buffers")
        if getattr(config, "tie_word_embeddings", False):
            raise NotImplementedError(
                "tie_word_embeddings is unsupported on the SPMD "
                "pipeline executor (embedding and head live on "
                "different stages); use a PipelineModule with "
                "TiedLayerSpec, or untie")
        self.n_stages = int(mesh.shape[PIPE_AXIS])
        self.mp = int(mesh.shape[MODEL_AXIS]) \
            if MODEL_AXIS in mesh.axis_names else 1
        if config.num_layers % self.n_stages != 0:
            raise ValueError(
                f"num_layers {config.num_layers} must divide evenly over "
                f"{self.n_stages} pipeline stages")
        if self.mp > 1:
            for name, dim in (("num_heads", config.num_heads),
                              ("hidden_size", config.hidden_size),
                              ("intermediate_size",
                               config.intermediate_size)):
                if dim % self.mp != 0:
                    raise ValueError(
                        f"{name} {dim} must divide over model-parallel "
                        f"size {self.mp}")
        self._M = M

        cos_sin = M._rotary_cache(config, config.max_seq_len)
        mp = self.mp

        def stage_fn(blocks_local, x):
            # scan over this stage's layers (leading dim of each leaf).
            def one(x, bp):
                cs = (cos_sin[0][:x.shape[1]], cos_sin[1][:x.shape[1]],
                      cos_sin[2])
                if mp > 1:
                    return M.block_forward_tp(config, bp, x, cs,
                                              MODEL_AXIS, mp,
                                              use_pallas=use_pallas), None
                return M.block_forward(config, bp, x, cs,
                                       use_pallas=use_pallas), None

            y, _ = jax.lax.scan(one, x, blocks_local)
            return y

        if mp > 1 and config.vocab_size % mp != 0:
            raise ValueError(
                f"vocab_size {config.vocab_size} must divide over "
                f"model-parallel size {mp}")

        def embed_fn(embed_params, tokens):
            wte = embed_params["wte"]
            if mp == 1:
                return wte[tokens]
            # Megatron VocabParallelEmbedding: each model rank holds a
            # contiguous vocab slice; out-of-range tokens contribute
            # zero, psum assembles the full embedding.
            v_local = wte.shape[0]
            start = jax.lax.axis_index(MODEL_AXIS) * v_local
            offset = tokens - start
            in_range = (offset >= 0) & (offset < v_local)
            safe = jnp.clip(offset, 0, v_local - 1)
            x = wte[safe] * in_range[..., None].astype(wte.dtype)
            return jax.lax.psum(x, MODEL_AXIS)

        def head_loss_fn(head_params, hidden, labels):
            h = M.layer_norm(hidden, head_params["final_ln"]["scale"],
                             head_params["final_ln"]["bias"],
                             config.layernorm_eps)
            wte = head_params["wte"]
            logits = jnp.einsum(
                "bsh,vh->bsv", h, wte.astype(h.dtype),
                preferred_element_type=jnp.float32)
            if mp == 1:
                return M.lm_loss(logits, labels)
            # Megatron vocab-parallel cross entropy: the [*, V/mp] logits
            # shard never leaves its rank — softmax stats and the target
            # logit travel as two scalars-per-token psums.
            logits = logits[:, :-1, :]
            targets = labels[:, 1:]
            v_local = wte.shape[0]
            start = jax.lax.axis_index(MODEL_AXIS) * v_local
            # the max shift is a pure stabilizer (lse is invariant to it),
            # so stop_gradient is exact; the cross-rank max goes through
            # all_gather because pmax has no differentiation rule
            local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
            m = jnp.max(jax.lax.all_gather(local_max, MODEL_AXIS), axis=0)
            z = jax.lax.psum(
                jnp.sum(jnp.exp(logits - m[..., None]), axis=-1),
                MODEL_AXIS)
            lse = jnp.log(z) + m
            valid = targets != -100
            offset = jnp.where(valid, targets, 0) - start
            in_range = (offset >= 0) & (offset < v_local)
            safe = jnp.clip(offset, 0, v_local - 1)
            picked_local = jnp.take_along_axis(
                logits, safe[..., None], axis=-1).squeeze(-1)
            picked = jax.lax.psum(
                picked_local * in_range.astype(jnp.float32), MODEL_AXIS)
            nll = (lse - picked) * valid
            return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)

        # One spec tree shared by the shard_map in_specs and the engine's
        # GSPMD placement (param_specs) so they can never drift.
        if mp > 1:
            self._tp_specs = {
                "embed": {"wte": P(MODEL_AXIS, None)},   # vocab-sharded
                "blocks": M.block_param_specs_tp(pipe_axis=PIPE_AXIS),
                "head": {"final_ln": {"scale": P(), "bias": P()},
                         "wte": P(MODEL_AXIS, None)},
            }
        else:
            self._tp_specs = None
        self.loss_fn = pipeline_loss_fn(
            embed_fn, stage_fn, head_loss_fn, mesh, n_micro, remat=remat,
            fp32_comm=fp32_comm, data_axis=DATA_AXIS,
            blocks_specs=self._tp_specs["blocks"] if mp > 1 else None,
            embed_specs=self._tp_specs["embed"] if mp > 1 else None,
            head_specs=self._tp_specs["head"] if mp > 1 else None,
            wire_latency=self.wire_latency)

    @staticmethod
    def stack_natural_params(params):
        """Natural GPTNeoX params ({embed, blocks: [per-layer dicts],
        final_ln, embed_out?}) -> the stacked pipeline layout this
        wrapper trains ({embed, blocks: [L, ...] leaves, head})."""
        if "head" in params and not isinstance(params.get("blocks"),
                                               (list, tuple)):
            return params   # already stacked
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *params["blocks"])
        head_wte = params["embed_out"]["wte"] if "embed_out" in params \
            else params["embed"]["wte"]
        return {
            "embed": {"wte": params["embed"]["wte"]},
            "blocks": stacked,
            "head": {"final_ln": dict(params["final_ln"]),
                     "wte": head_wte},
        }

    def init_params(self, rng):
        M, cfg = self._M, self.cfg
        keys = jax.random.split(rng, cfg.num_layers + 2)
        blocks = [M.init_block_params(cfg, keys[i + 1])
                  for i in range(cfg.num_layers)]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *blocks)
        return {
            "embed": {"wte": M._dense_init(keys[0], (cfg.vocab_size,
                                                     cfg.hidden_size),
                                           cfg.param_dtype)},
            "blocks": stacked,
            "head": {
                "final_ln": {
                    "scale": jnp.ones((cfg.hidden_size,), cfg.param_dtype),
                    "bias": jnp.zeros((cfg.hidden_size,), cfg.param_dtype),
                },
                "wte": M._dense_init(keys[-1], (cfg.vocab_size,
                                                cfg.hidden_size),
                                     cfg.param_dtype),
            },
        }

    def param_specs(self, params, mesh):
        if self.mp > 1:
            return self._tp_specs

        def blocks_spec(leaf):
            return P(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return {
            "embed": jax.tree_util.tree_map(lambda _: P(),
                                            params["embed"]),
            "blocks": jax.tree_util.tree_map(blocks_spec,
                                             params["blocks"]),
            "head": jax.tree_util.tree_map(lambda _: P(), params["head"]),
        }
