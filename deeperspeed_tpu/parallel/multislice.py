"""Multi-slice mesh composition over a DCN fabric (docs/multislice.md).

A "slice" is an ICI-connected accelerator island; slices are joined by
a data-center network ~10x slower than ICI. Following the MPMD-pipeline
mapping (arXiv:2412.14374), this module partitions an existing mesh
axis into named slices WITHOUT changing the mesh itself: collectives
keep their single-mesh semantics, and the slice structure only informs

  * the p2p wire policy (`runtime.pipe.p2p.configure_multislice` —
    which stage hops cross DCN and whether fp32 upcast is allowed
    there),
  * the analytic exposed-crossing model the `dcn_delay` fault kind
    charges (`parallel.schedule.dcn_exposed_crossings`),
  * the elastic layer's unit of staleness escalation
    (`PeerHealthMonitor.set_slice_map` — a dead host kills its whole
    slice's ICI mesh, so the SLICE is what fails), and
  * slice-loss recovery (`elasticity.slices.repartition_after_slice_loss`
    — surviving slices re-partition through the natural-layout
    checkpoint stage-change path).

No collective is ever issued from this module: slice-aware code paths
route every wire operation through the schedule pass (enforced by the
`multislice-collective-outside-schedule` dslint rule).
"""

import copy
import logging

from .schedule import dcn_exposed_crossings

logger = logging.getLogger(__name__)


class SliceTopology:
    """Static slice partition of one mesh axis.

    axis="pipe": ``n_slices`` contiguous equal spans of the pipeline
    stages; ``stage_boundaries`` holds every stage index ``s`` whose
    forward hop ``s -> s+1`` crosses a slice boundary (the wrap-around
    hop ``last -> 0`` is the 1F1B ppermute's ring edge and crosses
    whenever slices > 1 — it is counted separately by the exposed-
    crossing model as part of the same ring).

    axis="data": slices split the dp axis; there are no stage spans and
    ``n_boundaries`` DCN cuts sit inside the dp reduction ring.
    """

    def __init__(self, names, axis, n_stages=None, peer_map=None):
        self.names = list(names)
        self.axis = axis
        self.n_slices = len(self.names)
        if self.n_slices < 2:
            raise ValueError("a SliceTopology needs >= 2 slices")
        self.n_stages = n_stages
        self.stage_spans = {}
        self.stage_boundaries = ()
        if axis == "pipe":
            if not n_stages or n_stages % self.n_slices != 0:
                raise ValueError(
                    f"slices ({self.n_slices}) must divide the stage "
                    f"count ({n_stages})")
            per = n_stages // self.n_slices
            self.stage_spans = {
                name: (i * per, (i + 1) * per)
                for i, name in enumerate(self.names)}
            self.stage_boundaries = tuple(
                i * per - 1 for i in range(1, self.n_slices))
        # peer_map: heartbeat peer name -> slice name (the escalation
        # unit); empty when the config carries no slice_peers
        self.peer_map = dict(peer_map or {})

    @classmethod
    def from_config(cls, ms_cfg, pipeline_config=None):
        """Build from a validated `multislice_config` dict
        (`runtime.config._parse_multislice_block`)."""
        axis = ms_cfg["axis"]
        n_stages = (pipeline_config["stages"]
                    if axis == "pipe" and pipeline_config else None)
        peer_map = {}
        for sname, peers in (ms_cfg["slice_peers"] or {}).items():
            for p in peers:
                peer_map[p] = sname
        return cls(ms_cfg["names"], axis, n_stages=n_stages,
                   peer_map=peer_map)

    @property
    def n_boundaries(self):
        """DCN cuts in the slice ring (= slices - 1 for the linear
        chain both mappings model)."""
        return self.n_slices - 1

    def slice_of_stage(self, stage):
        """Slice name owning pipeline stage `stage` (axis="pipe")."""
        for name, (lo, hi) in self.stage_spans.items():
            if lo <= stage < hi:
                return name
        raise ValueError(f"stage {stage} outside 0..{self.n_stages - 1}")

    def slice_of_peer(self, peer):
        """Slice name a heartbeat peer maps to, or None if unmapped
        (e.g. the COORDINATOR pseudo-peer — its loss is a coordination
        failure, never a slice failure)."""
        return self.peer_map.get(peer)

    def peers_of(self, slice_name):
        """Heartbeat peers mapped to `slice_name` (may be empty)."""
        return [p for p, s in self.peer_map.items() if s == slice_name]

    def exposed_crossings(self, n_micro, wire_latency):
        """Schedule-aware exposed DCN crossings per optimizer step —
        see `parallel.schedule.dcn_exposed_crossings`."""
        return dcn_exposed_crossings(self.n_boundaries, n_micro,
                                     wire_latency,
                                     pipelined=(self.axis == "pipe"))

    def cross_slice_p2p_bytes(self, act_bytes, n_micro):
        """Analytic bytes per step over DCN for the 1F1B stage-boundary
        p2p: each micro-batch's activation crosses every boundary once
        forward and its cotangent once backward."""
        if self.axis != "pipe":
            return 0
        return 2 * int(n_micro) * self.n_boundaries * int(act_bytes)

    def surviving(self, lost):
        """Topology after losing `lost` (iterable of slice names):
        (surviving names, surviving stage count). Raises if nothing
        survives — that is a job loss, not a re-partition."""
        lost = set(lost)
        unknown = sorted(lost - set(self.names))
        if unknown:
            raise ValueError(f"unknown slice(s) {unknown}")
        keep = [n for n in self.names if n not in lost]
        if not keep:
            raise ValueError("all slices lost — nothing to re-partition")
        stages = None
        if self.axis == "pipe":
            per = self.n_stages // self.n_slices
            stages = per * len(keep)
        return keep, stages

    def __repr__(self):
        return (f"SliceTopology(axis={self.axis!r}, "
                f"names={self.names!r}, spans={self.stage_spans!r})")


def surviving_raw_config(raw_config, topology, lost):
    """Re-partitioned raw config dict for the surviving slices: the
    pipeline block shrinks to the surviving stage count and the
    multislice block shrinks (or drops, when one slice remains) — the
    natural-layout checkpoint stage-change path absorbs the rest
    (docs/multislice.md walkthrough)."""
    keep, stages = topology.surviving(lost)
    cfg = copy.deepcopy(dict(raw_config))
    if topology.axis == "pipe":
        if stages < 2:
            raise ValueError(
                "surviving pipeline would have < 2 stages — the "
                "checkpoint layout guard rejects pipeline -> "
                "sequential re-partition (keep >= 2 stages per slice)")
        cfg["pipeline"]["stages"] = stages
        # micro_batches and comm_overlap carry over unchanged
    ms = cfg.get("multislice")
    if ms is not None:
        if len(keep) < 2:
            del cfg["multislice"]
        else:
            ms = dict(ms)
            ms["slices"] = len(keep)
            ms["names"] = list(keep)
            peers = ms.get("slice_peers")
            if peers:
                ms["slice_peers"] = {
                    s: list(p) for s, p in peers.items() if s in keep}
                if not ms["slice_peers"]:
                    ms.pop("slice_peers")
            cfg["multislice"] = ms
    # injected faults that acted on the LOST topology must not re-fire
    # (or fail validation) in the survivor: slice_kill entries naming a
    # lost slice go always; every multislice fault kind goes when the
    # block itself was dropped
    fi = (cfg.get("training_health") or {}).get("fault_injection")
    if fi and fi.get("faults"):
        from ..runtime.fault_injection import MULTISLICE_FAULT_KINDS
        kept_faults = []
        for f in fi["faults"]:
            kind = f.get("kind")
            if kind in MULTISLICE_FAULT_KINDS and "multislice" not in cfg:
                continue
            if kind == "slice_kill" and f.get("slice") not in keep:
                continue
            kept_faults.append(f)
        fi["faults"] = kept_faults
    logger.warning(
        "multislice re-partition: lost %s, surviving %s (stages=%s)",
        sorted(set(lost)), keep, stages)
    return cfg
