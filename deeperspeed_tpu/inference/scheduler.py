"""Continuous-batching scheduler for the serving engine.

Per engine step the scheduler builds ONE `StepPlan`: a (possibly empty)
prefill batch of newly admitted requests plus the decode batch of every
in-flight sequence — at FIXED compiled shapes. Batch and length are
bucketed (`prefill_lengths`, `prefill_batch_sizes`,
`decode_batch_sizes`), so after the bucket ladder has warmed up, XLA
never recompiles no matter how requests arrive (`InferenceEngine.
compile_count` pins this in tests).

Admission policy (in order, per step):

1. Every running sequence decodes this step — decode is never starved
   by prefill. A sequence crossing into a page it does not own yet gets
   one page from the pool first; if the pool is empty, the YOUNGEST
   running request is evicted (pages freed, request requeued at the
   front of the waiting queue with its generated prefix intact as
   prompt) until the allocation succeeds — oldest work finishes first,
   and an evicted request re-prefills its whole context on readmission.
2. Waiting requests admit FIFO while (a) the step's token budget holds
   — a prefill costs its padded bucket length, a decode costs 1 token —
   (b) a decode slot is free (`max_batch_size` bounds in-flight
   sequences), (c) the prefill batch bucket has room, and (d) the pool
   can hand the request all pages of its padded prompt bucket up front
   (the whole-page prefill scatter writes every bucket page, and the
   tail pages double as growth room — no per-token allocation until the
   sequence outgrows its bucket). One prefill call runs ONE length
   bucket: shorter queued prompts pad up into the batch's bucket, a
   longer one closes the batch and leads the next step's.

Token accounting uses PADDED bucket sizes, not raw prompt lengths: the
budget is a compute bound, and compute is spent at compiled shapes.
The budget must cover the largest user prefill bucket (validated at
init — a smaller budget could never admit such a prompt); an evicted
request whose regrown context buckets above the user ladder is exempt
from the budget for the step's first prefill, so the queue can never
wedge behind it.

Robustness layer (docs/inference.md "Serving under failure"):

- every request reaches exactly ONE terminal status — ``ok`` /
  ``shed`` / ``deadline_exceeded`` / ``failed`` (`Request.status`;
  single assignment enforced) — surfaced via `pop_finished()` and the
  per-status ``Serve/requests_*`` counters;
- requests carrying a ``deadline_ms`` are expired at the top of every
  `schedule()` (waiting AND running) with a typed `DeadlineExceeded`
  instead of consuming further decode cadence;
- eviction picks the LOWEST-priority / LATEST-deadline victim
  (`_evict_victim`) instead of blanket youngest-first — ``batch``
  traffic is preempted before ``interactive``, and within a class the
  request with the most deadline slack goes first (youngest as the
  final tiebreak, preserving the original policy for homogeneous
  streams);
- step-failure quarantine: the engine parks implicated requests here
  (`quarantine_request`) with a capped-jittered ``retry_at``; they
  re-admit at the queue front once eligible (eviction-regrowth
  machinery reused: budget exemption, drain re-admission).

Serving-speedup layer (docs/inference.md "Prefix/radix cache" +
"Speculative decoding"; both default-off):

- with a `PrefixCache`, `add_request` (retried at admission) attaches
  the longest registered page chain matching the prompt — the request
  shares those pages by refcount and its prefill covers only the
  SUFFIX (a "chunk" step plan); `complete_prefill` registers the full
  prompt pages back into the chain;
- with ``spec_tokens`` = k > 0, decode rows budget/grow for a k-token
  draft window; `complete_speculative` applies the accepted run and
  rolls tail pages the next window cannot reach back to the allocator.
"""

import math
from collections import deque
from dataclasses import dataclass, field

from .admission import (DeadlineExceeded, PRIORITY_RANK, STATUS_DEADLINE,
                        STATUS_FAILED, STATUS_OK)
from .kv_cache import pages_for_tokens

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"


@dataclass
class Request:
    """One generation request. `prompt` is a list/array of token ids."""
    prompt: list
    max_new_tokens: int
    request_id: object = None
    eos_token_id: int = None
    # SLO contract (admission.py): priority class, wall-clock deadline,
    # TTFT service objective — all optional
    priority: str = "interactive"
    deadline_ms: float = None
    ttft_slo_ms: float = None
    # runtime state (owned by the scheduler/engine)
    generated: list = field(default_factory=list)
    pages: list = field(default_factory=list)
    cached: int = 0          # tokens whose K/V sit in `pages`
    # prefix-cache attachment: the first `n_shared` entries of `pages`
    # are registry pages this request only READS (retained, never
    # written); `prefix_node` is the deepest matched/registered chain
    # node (kv_cache.PrefixCache)
    n_shared: int = 0
    prefix_node: object = None
    state: str = WAITING
    evictions: int = 0
    enqueued_at: float = None
    admitted_at: float = None
    deadline_at: float = None   # absolute clock: enqueue + deadline_ms
    # terminal outcome: exactly one of ok/shed/deadline_exceeded/failed,
    # assigned once; non-ok outcomes carry the typed error
    status: str = None
    error: Exception = None
    # step-failure quarantine bookkeeping (engine `_quarantine_batch`):
    # consecutive failed steps (reset on any completed step) and the
    # earliest re-admission time of the current backoff window
    failures: int = 0
    retry_at: float = None
    # request-level latency observability (inference/metrics.py):
    # submitted_at survives evictions (TTFT measures from first submit,
    # once); last_token_at feeds the inter-token histogram
    submitted_at: float = None
    first_token_at: float = None
    last_token_at: float = None

    @property
    def context(self):
        """Prompt + generated so far (what an eviction re-prefills)."""
        return list(self.prompt) + list(self.generated)

    @property
    def done(self):
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and self.generated and
                self.generated[-1] == self.eos_token_id)


@dataclass
class StepPlan:
    """One engine step at fixed compiled shapes."""
    prefills: list            # requests entering this step
    prefill_batch: int        # batch bucket (0 = no prefill this step)
    prefill_len: int          # length bucket
    decodes: list             # in-flight requests decoding this step
    decode_batch: int         # batch bucket (0 = no decode this step)
    evicted: list             # requests preempted while planning
    # "full" = whole-context prefill (every page written); "chunk" =
    # prefix-cache suffix prefill (rows carry shared pages that are
    # only read; prefill_len buckets the SUFFIX) — one kind per call,
    # like the length bucket
    prefill_kind: str = "full"

    @property
    def empty(self):
        return not self.prefills and not self.decodes


def _bucket(value, buckets):
    """Smallest bucket >= value; None when value exceeds the ladder."""
    for b in buckets:
        if value <= b:
            return b
    return None


class ContinuousBatchingScheduler:
    """Admission/eviction over a `PagedKVCache` pool under a per-step
    token budget. Host-side and deterministic: the same request arrival
    order always produces the same step plans (the serving bench's
    fixed-seed open-loop stream relies on this)."""

    def __init__(self, cache, max_seq_len, token_budget, max_batch_size,
                 prefill_lengths, prefill_batch_sizes, decode_batch_sizes,
                 prefix_cache=None, spec_tokens=0):
        self.cache = cache
        self.page_size = cache.page_size
        self.max_seq_len = int(max_seq_len)
        self.token_budget = int(token_budget)
        self.max_batch_size = int(max_batch_size)
        # prefix/radix reuse (kv_cache.PrefixCache) and speculative
        # decoding (k draft tokens verified per decode step); both off
        # by default — the plain PR 8 behavior is bit-identical then
        self.prefix_cache = prefix_cache
        self.spec_tokens = int(spec_tokens)
        if self.max_seq_len % self.page_size:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} is not a multiple of "
                f"page_size {self.page_size}: the page-aligned re-prefill "
                f"ladder could not cover a context in the misaligned "
                f"tail, so an evicted request there could never readmit")
        self.prefill_lengths = sorted(int(b) for b in prefill_lengths)
        self.prefill_batch_sizes = sorted(int(b) for b in
                                          prefill_batch_sizes)
        self.decode_batch_sizes = sorted(int(b) for b in decode_batch_sizes)
        for length in self.prefill_lengths:
            if length % self.page_size:
                raise ValueError(
                    f"prefill length bucket {length} is not a multiple "
                    f"of page_size {self.page_size} (the prefill scatter "
                    f"writes whole pages)")
        if self.token_budget < self.prefill_lengths[-1]:
            raise ValueError(
                f"token_budget {self.token_budget} is smaller than the "
                f"largest prefill bucket {self.prefill_lengths[-1]}: a "
                f"prompt in that bucket could never be admitted (the "
                f"queue would livelock)")
        # Re-prefill ladder: an evicted request's context (prompt +
        # generated) can legitimately outgrow the user ladder while
        # staying under max_seq_len, so extend it with doubled
        # page-aligned buckets up to the (page-aligned, validated
        # above) window. Readmission then always has a shape; the
        # doubling keeps the lazily compiled program set logarithmic,
        # and eviction-regrowth is the only path that ever warms these
        # extra buckets.
        top = self.max_seq_len
        ladder = set(self.prefill_lengths)
        length = self.prefill_lengths[-1]
        while length < top:
            length = min(length * 2, top)
            ladder.add(length)
        self._prefill_ladder = sorted(ladder)
        self.waiting = deque()
        self.running = []
        self.finished = []
        self.quarantined = []    # step-failure backoff (retry_at gates)
        self.status_counts = {STATUS_OK: 0, STATUS_DEADLINE: 0,
                              STATUS_FAILED: 0}
        self._counter = 0
        self.draining = False

    # -- intake ------------------------------------------------------------

    def add_request(self, request, now=None):
        prompt_len = len(request.prompt)
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got "
                f"{request.max_new_tokens} (prefill always samples the "
                f"first token)")
        if _bucket(prompt_len, self.prefill_lengths) is None:
            raise ValueError(
                f"prompt length {prompt_len} exceeds the largest prefill "
                f"bucket {self.prefill_lengths[-1]}")
        if prompt_len + request.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt {prompt_len} + max_new_tokens "
                f"{request.max_new_tokens} exceeds max_seq_len "
                f"{self.max_seq_len}")
        if request.request_id is None:
            request.request_id = self._counter
        self._counter += 1
        request.state = WAITING
        request.enqueued_at = now
        if request.submitted_at is None:
            request.submitted_at = now
        if request.deadline_at is None and request.deadline_ms is not None \
                and now is not None:
            request.deadline_at = now + float(request.deadline_ms) / 1e3
        if self.prefix_cache is not None:
            self._attach_prefix(request, count_lookup=True)
        self.waiting.append(request)
        return request.request_id

    # -- prefix/radix cache (kv_cache.PrefixCache) -------------------------

    def _attach_prefix(self, request, count_lookup=False):
        """Share the longest registered page chain matching the
        prompt: bump refcounts and start the request's page list with
        the shared pages — its prefill then covers only the suffix.
        Runs at `add_request` and retried at admission for misses (the
        registry may have warmed in between); hit/shared stats count
        once per request either way."""
        pc = self.prefix_cache
        if count_lookup:
            pc.stats["lookups"] += 1
        chain = pc.lookup(request.prompt)
        if not chain:
            return
        pages = [n.page for n in chain]
        self.cache.retain(pages)
        request.pages = list(pages)
        request.n_shared = len(pages)
        request.prefix_node = chain[-1]
        pc.stats["hits"] += 1
        pc.stats["pages_shared"] += len(pages)
        pc.stats["saved_prefill_tokens"] += len(pages) * self.page_size

    def _register_prefix(self, request):
        """After a completed prefill, register every FULL prompt page
        not already covered by the matched chain (generated tokens and
        partial tail pages never register — their content is not a pure
        function of the prompt prefix)."""
        ps = self.page_size
        n_full = len(request.prompt) // ps
        if n_full <= request.n_shared:
            return
        keys = [self.prefix_cache.page_key(request.prompt[i * ps:
                                                          (i + 1) * ps])
                for i in range(request.n_shared, n_full)]
        pages = request.pages[request.n_shared:n_full]
        request.prefix_node = self.prefix_cache.register(
            request.prefix_node, keys, pages)

    def detach_waiting_prefixes(self):
        """Drop prefix attachments from every not-yet-admitted request
        (waiting + quarantined): on a weight hot-swap or pool loss the
        shared pages' K/V no longer matches the model, so the requests
        must re-prefill their full prompt. Admitted (running) requests
        are the engine's problem — it evicts them on pool loss."""
        for req in list(self.waiting) + list(self.quarantined):
            if req.n_shared:
                self.cache.free(req.pages[:req.n_shared])
                req.pages = req.pages[req.n_shared:]
                req.n_shared = 0
            req.prefix_node = None

    @property
    def has_work(self):
        return bool(self.waiting or self.running or self.quarantined)

    # -- graceful drain ----------------------------------------------------

    def stop_admissions(self):
        """Graceful-drain mode (SIGTERM): `schedule()` stops admitting
        FRESH requests from the queue. Eviction-regrowth re-prefills
        (evicted in-flight sequences, whose K/V must be rebuilt to
        finish) still admit — they count as in-flight work."""
        self.draining = True

    @property
    def has_inflight_work(self):
        """Work a graceful drain should still finish: running sequences,
        evicted ones awaiting re-prefill, and quarantined ones awaiting
        a retry (their generation is partial). Fresh queued requests do
        NOT count — a draining server leaves them for the replacement
        instance."""
        return bool(self.running or self.quarantined or
                    any(r.evictions for r in self.waiting))

    def inflight_requests(self):
        """Every request a drain is still responsible for (the
        complement of the fresh queued ones `has_inflight_work`
        excludes) — what the drain-deadline path fails with a typed
        terminal status instead of silently abandoning."""
        return (list(self.running) + list(self.quarantined) +
                [r for r in self.waiting if r.evictions])

    def pop_finished(self):
        """Drain completed requests (the caller owns them afterwards).
        Long-lived serving loops must consume this (the engine's
        `generate` does) — `finished` otherwise grows without bound."""
        out, self.finished = self.finished, []
        return out

    # -- terminal statuses -------------------------------------------------

    def _release_pages(self, request):
        """Drop every page reference a request holds (owned AND
        prefix-shared — shared pages just lose one refcount and live on
        under the registry) and reset its prefix attachment."""
        self.cache.free(request.pages)
        request.pages = []
        request.n_shared = 0
        request.prefix_node = None

    def _finish(self, request, status, error=None):
        """The ONLY exit gate: pull the request out of whatever
        collection holds it, free its pages, and stamp its terminal
        status exactly once (a second assignment is an invariant
        violation, raised loudly — the chaos soak pins this)."""
        if request.status is not None:
            raise RuntimeError(
                f"request {request.request_id} already reached terminal "
                f"status {request.status!r}; refusing to overwrite with "
                f"{status!r}")
        if request in self.running:
            self.running.remove(request)
        if request in self.quarantined:
            self.quarantined.remove(request)
        try:
            self.waiting.remove(request)
        except ValueError:
            pass
        self._release_pages(request)
        request.status = status
        if error is not None:
            request.error = error
        request.state = FINISHED
        self.status_counts[status] += 1
        self.finished.append(request)

    def finish_failed(self, request, error):
        """Terminal step failure (poison / drain abort): status
        ``failed`` with the typed error attached."""
        self._finish(request, STATUS_FAILED, error)

    # -- deadline expiry ---------------------------------------------------

    def expire_deadlines(self, now=None):
        """Terminate every request whose ``deadline_ms`` elapsed —
        waiting, quarantined, or running — with a typed
        `DeadlineExceeded` and status ``deadline_exceeded``. Runs at
        the top of every `schedule()` so an expired request never
        consumes another decode step. Returns the expired requests."""
        if now is None:
            return []
        expired = [r for r in list(self.waiting) + list(self.quarantined)
                   + list(self.running)
                   if r.deadline_at is not None and now >= r.deadline_at]
        for req in expired:
            self._finish(req, STATUS_DEADLINE, DeadlineExceeded(
                f"request {req.request_id} missed its deadline "
                f"(deadline_ms={req.deadline_ms}) with "
                f"{len(req.generated)}/{req.max_new_tokens} tokens "
                f"generated"))
        return expired

    # -- step-failure quarantine (engine `_quarantine_batch`) --------------

    def quarantine_request(self, request, retry_at, now=None):
        """Park a step-failed request for a capped-jittered retry:
        evict it (pages freed, full-context re-prefill on readmission —
        the eviction machinery's budget exemption and drain
        re-admission apply) but gate re-admission on ``retry_at``."""
        if request in self.running:
            self.running.remove(request)
        try:
            # cache-loss recovery may have already evicted it into the
            # waiting queue — it must not sit in BOTH collections
            self.waiting.remove(request)
        except ValueError:
            pass
        self._release_pages(request)
        request.cached = 0
        request.evictions += 1
        request.state = WAITING
        request.enqueued_at = now
        request.retry_at = float(retry_at)
        self.quarantined.append(request)

    def admit_handoff(self, request, now=None):
        """Admit a request whose prefill happened on ANOTHER pool
        (disaggregated serving): its pages are already allocated and
        written, its first token already sampled — it enters `running`
        directly, bypassing admission and the prefill queue. The
        decode-role drain gate does not apply: a handed-off request IS
        in-flight work."""
        if request.request_id is None:
            request.request_id = self._counter
        self._counter += 1
        request.state = RUNNING
        request.admitted_at = now
        self.running.append(request)

    def requeue_handoff(self, request, now=None):
        """Put a request whose handoff failed (rejected / timed-out
        offer) back at the FRONT of the waiting queue with eviction
        semantics: pages freed, K/V rebuilt by a full-context
        re-prefill, then a fresh offer. `evictions` counting keeps it
        admissible through a prefill-pool drain."""
        if request in self.running:
            self.running.remove(request)
        self._release_pages(request)
        request.cached = 0
        request.evictions += 1
        request.state = WAITING
        request.enqueued_at = now
        self.waiting.appendleft(request)

    def _release_quarantined(self, now):
        """Move backoff-expired quarantined requests to the FRONT of
        the waiting queue (like any evicted request — their partial
        generation finishes before fresh work starts)."""
        if not self.quarantined or now is None:
            return
        due = [r for r in self.quarantined if r.retry_at is None or
               now >= r.retry_at]
        for req in due:
            self.quarantined.remove(req)
            req.retry_at = None
            self.waiting.appendleft(req)

    # -- planning ----------------------------------------------------------

    def _evict_victim(self, now=None):
        """Preempt the lowest-priority / latest-deadline running
        request: free its pages and requeue it (front of the queue,
        full context as the new prompt). Victim order: ``batch`` before
        ``interactive``; within a class, the request with the MOST
        deadline slack (no deadline = infinite slack) goes first;
        youngest-first as the final tiebreak (the pre-robustness
        policy, preserved exactly for homogeneous streams). Returns the
        request, or None if nothing to evict."""
        if not self.running:
            return None
        req = max(
            enumerate(self.running),
            key=lambda kv: (PRIORITY_RANK.get(kv[1].priority, 0),
                            kv[1].deadline_at if kv[1].deadline_at
                            is not None else math.inf,
                            kv[0]))[1]
        self.running.remove(req)
        self._release_pages(req)
        req.cached = 0
        req.evictions += 1
        req.state = WAITING
        # admission wait restarts from the requeue, else readmission
        # re-counts the first wait AND the time spent running
        req.enqueued_at = now
        self.waiting.appendleft(req)
        return req

    # youngest-first was the pre-robustness policy; the name survives
    # for callers/tests that drive an explicit eviction round-trip
    _evict_youngest = _evict_victim

    def _spec_window(self, req):
        """Draft tokens to propose for `req` this step: the configured
        k, capped so (a) the request can still USE that many — accepting
        w drafts appends w+1 tokens, bounded by max_new_tokens — and
        (b) every window position cached..cached+w stays inside the
        serving window. 0 when speculation is off (or the request can
        only take one more token: plain decode)."""
        if not self.spec_tokens:
            return 0
        remaining = req.max_new_tokens - len(req.generated)
        return max(0, min(self.spec_tokens, remaining - 1,
                          self.max_seq_len - 1 - req.cached))

    def _grow_running(self, evicted, now=None):
        """Give every running sequence the page(s) its next step needs
        — one token, or the whole speculative window cached..cached+w;
        evict youngest-first when the pool runs dry. A sequence can
        never evict itself out of existence: with one running request
        the pool math guarantees its page fits or the config was
        rejected at engine init."""
        for req in list(self.running):
            if req not in self.running:           # evicted by an earlier turn
                continue
            # last slot this step's writes reach (the speculative
            # verify writes the full window before acceptance)
            pos = req.cached + self._spec_window(req)
            page_idx = pos // self.page_size
            while page_idx >= len(req.pages):
                got = self.cache.allocate(1)
                if got is not None:
                    req.pages.extend(got)
                    continue
                victim = self._evict_youngest(now)
                if victim is None:
                    raise RuntimeError(
                        "page pool exhausted with nothing left to evict "
                        "— num_pages is too small for max_seq_len")
                evicted.append(victim)
                if victim is req:                 # req evicted itself
                    break

    def schedule(self, now=None):
        """Build this step's `StepPlan` (see the module docstring for
        the policy). Mutates scheduler state: admitted requests move to
        `running` with pages allocated; evicted ones back to `waiting`;
        deadline-expired ones terminate first (typed, never another
        decode step); backoff-expired quarantined ones re-enter the
        queue front."""
        self.expire_deadlines(now)
        self._release_quarantined(now)
        evicted = []
        self._grow_running(evicted, now)
        decodes = list(self.running)
        # a decode step costs 1 token per row — plus its speculative
        # window: the verify forward computes window+1 positions
        budget = self.token_budget - sum(1 + self._spec_window(r)
                                         for r in decodes)

        prefills = []
        step_len = 0
        step_kind = "full"
        max_prefill_batch = self.prefill_batch_sizes[-1]
        while self.waiting and len(prefills) < max_prefill_batch and \
                len(self.running) < self.max_batch_size:
            req = self.waiting[0]
            if self.draining and not req.evictions:
                # drain: fresh requests stay queued (the front of the
                # queue is fresh ⇒ everything behind it is too — evicted
                # requests requeue at the FRONT)
                break
            if self.prefix_cache is not None and not req.n_shared and \
                    not req.evictions and not req.generated:
                # miss at submit time — the registry may have warmed
                # since (the bursty shared-prefix case: the whole burst
                # queues before the first prefill registers)
                self._attach_prefix(req)
            # a prefix-attached request prefills only its SUFFIX (the
            # shared pages already hold the prefix K/V): bucket that
            req_kind = "chunk" if req.n_shared else "full"
            suffix_len = len(req.context) - req.n_shared * self.page_size
            length = _bucket(suffix_len, self._prefill_ladder)
            if length is None:
                # unreachable: the ladder tops at the aligned window and
                # running contexts stay below it (_maybe_finish) — kept
                # as a loud invariant guard rather than a queue wedge
                self.finish_failed(req, RuntimeError(
                    "context outgrew the prefill bucket ladder"))
                raise RuntimeError(
                    f"request {req.request_id} context "
                    f"({len(req.context)} tokens) outgrew the prefill "
                    f"bucket ladder after eviction; raise "
                    f"prefill_lengths or num_pages")
            # one length bucket AND one kind per prefill call: shorter
            # prompts pad up into the batch's bucket, a LONGER one (or
            # a kind mismatch — the chunk and full programs have
            # different shapes) waits for the next step
            if prefills and (length > step_len or req_kind != step_kind):
                break
            row_len = step_len if prefills else length
            if row_len > budget and (prefills or not req.evictions):
                # the step's first prefill is budget-exempt for EVICTED
                # requests: their regrown context can bucket above the
                # user ladder (and the validated budget floor), and they
                # requeue at the queue front — holding them to the
                # budget would wedge the queue behind them forever
                break
            pages = self.cache.allocate(pages_for_tokens(row_len,
                                                         self.page_size))
            if pages is None:
                break                      # pool full: wait for completions
            budget -= row_len
            step_len = row_len
            step_kind = req_kind
            self.waiting.popleft()
            # shared prefix pages (if any) stay in front; the freshly
            # allocated suffix/bucket pages follow — page i of the list
            # always holds context tokens [i·ps, (i+1)·ps)
            req.pages = req.pages + pages
            req.cached = 0
            req.state = RUNNING
            req.admitted_at = now
            self.running.append(req)
            prefills.append(req)

        prefill_len = step_len if prefills else 0
        prefill_batch = (_bucket(len(prefills), self.prefill_batch_sizes)
                         if prefills else 0)
        decode_batch = (_bucket(len(decodes), self.decode_batch_sizes)
                        if decodes else 0)
        if decodes and decode_batch is None:
            raise RuntimeError(
                f"{len(decodes)} in-flight sequences exceed the decode "
                f"bucket ladder {self.decode_batch_sizes}")
        return StepPlan(prefills=prefills, prefill_batch=prefill_batch or 0,
                        prefill_len=prefill_len, decodes=decodes,
                        decode_batch=decode_batch or 0, evicted=evicted,
                        prefill_kind=step_kind)

    # -- results -----------------------------------------------------------

    def complete_prefill(self, request, first_token):
        """Record a prefill's result: the prompt's K/V is cached and the
        first generated token sampled."""
        request.cached = len(request.context)
        if self.prefix_cache is not None:
            self._register_prefix(request)
        request.generated.append(int(first_token))
        request.failures = 0     # a completed step ends the failure run
        self._maybe_finish(request)

    def complete_decode(self, request, token):
        """Record a decode step: the previous token's K/V entered the
        cache at slot `cached`, and `token` was sampled from it."""
        request.cached += 1
        request.generated.append(int(token))
        request.failures = 0
        self._maybe_finish(request)

    def complete_speculative(self, request, tokens):
        """Record one speculative window: `tokens` are the accepted
        draft tokens plus the verifier's correction/bonus token, in
        order. Each appended token's PREDECESSOR has its K/V in the
        cache (the verify forward wrote the whole window), so `cached`
        advances one per append — exactly the sequential `complete_
        decode` accounting, n times. Appending stops at the request's
        natural end (eos / max_new_tokens / window), dropping the rest
        of the accepted tokens; surviving requests then roll back the
        tail pages the next window can no longer reach. Returns the
        number of tokens actually appended."""
        appended = 0
        for t in tokens:
            request.cached += 1
            request.generated.append(int(t))
            appended += 1
            total = len(request.prompt) + len(request.generated)
            if request.done or total >= self.max_seq_len:
                break
        request.failures = 0
        self._maybe_finish(request)
        if request.status is None:
            self._rollback_spec_pages(request)
        return appended

    def _rollback_spec_pages(self, request):
        """Release owned tail pages past the NEXT speculative window's
        horizon — the allocator-rollback of pages grown for rejected
        tokens the shrinking window (max_new_tokens nearly spent, or
        the serving window's edge) will never write again. Growth and
        rollback use the same horizon, so pages a full-k window still
        needs are kept, not churned. Shared prefix pages are never
        rolled back."""
        if not self.spec_tokens:
            return
        limit = min(request.cached + self._spec_window(request),
                    self.max_seq_len - 1)
        needed = max(limit // self.page_size + 1, request.n_shared)
        while len(request.pages) > needed:
            self.cache.free([request.pages.pop()])

    def _maybe_finish(self, request):
        total = len(request.prompt) + len(request.generated)
        if request.done or total >= self.max_seq_len:
            self._finish(request, STATUS_OK)
