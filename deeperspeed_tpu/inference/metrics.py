"""Request-level serving observability.

The serving engine's ``Serve/*`` counters (PR 8) are aggregates — total
prefill tokens, cumulative phase seconds. Operating a fleet needs
*distributions*: a p99 TTFT regression is invisible in a mean. This
module keeps fixed-bucket histograms (the same bucket ladder the
Prometheus exporter renders, so in-process percentiles and the scrape
agree) for the three per-request latencies:

- **admission wait**: enqueue → admitted (scheduler queueing delay;
  re-counted from the requeue after an eviction, matching the
  scheduler's `enqueued_at` reset);
- **TTFT** (time to first token): submit → first sampled token, once
  per request (an evicted request's re-prefill does not re-count it);
- **inter-token**: gap between consecutive sampled tokens of one
  request (the decode cadence users actually feel).

Every observation is also forwarded to the monitor's export backends
(`TensorBoardMonitor.observe_histogram`) so the Prometheus endpoint
serves ``Serve/*`` histogram families with bucket counts, sum, and
count. Host floats only — the serving loop already measured these on
the host, nothing here touches a device value.
"""

from ..runtime.exporters import LATENCY_BUCKETS_MS, Histogram
from .admission import REQUEST_STATUSES

# monitor/Prometheus family names
ADMISSION_WAIT = "Serve/admission_wait_ms"
TTFT = "Serve/ttft_ms"
INTER_TOKEN = "Serve/inter_token_ms"
# disaggregated handoff (PR 20): offer-publish → ack-receipt round
# trip, observed on the prefill pool (inference/handoff.py)
HANDOFF = "Serve/handoff_ms"

# front-end router gauge families (inference/router.py): cumulative
# routed/shed counts, the cross-pool handoff p50, per-pool load scores,
# and the advisory autoscaling bit — recorded as monitor scalars by
# `ServeRouter.serve_stats` (latest-value gauges on the scrape)
ROUTER_ROUTED = "Serve/router/routed"
ROUTER_SHED = "Serve/router/shed"
ROUTER_HANDOFF_MS = "Serve/router/handoff_ms"
ROUTER_POOL_LOAD = "Serve/router/load"
ROUTER_ADVISE_SCALE_UP = "Serve/router/advise_scale_up"

# prefix-cache / speculative-decode gauge families (PR 16): recorded as
# monitor scalars every step, like REQUEST_STATUS_FAMILIES below —
# latest-value gauges on the Prometheus scrape
PREFIX_HIT_RATE = "Serve/prefix_cache/hit_rate"
PREFIX_PAGES_SHARED = "Serve/prefix_cache/pages_shared"
PREFIX_SAVED_PREFILL_TOKENS = "Serve/prefix_cache/saved_prefill_tokens"
SPEC_ACCEPTANCE_RATE = "Serve/speculative/acceptance_rate"

# per-terminal-status request counters (admission.REQUEST_STATUSES):
# the engine records these every step as monitor scalars, so they ride
# the single buffered drain into EVERY export backend — latest-value
# gauges on the Prometheus scrape, per-drain events on the JSONL stream
REQUEST_STATUS_FAMILIES = {
    status: f"Serve/requests_{status}" for status in REQUEST_STATUSES}


class ServeRequestMetrics:
    """Fixed-bucket latency histograms + monitor fan-out."""

    def __init__(self, monitor=None, buckets=LATENCY_BUCKETS_MS):
        self.monitor = monitor
        self.admission_wait = Histogram(buckets)
        self.ttft = Histogram(buckets)
        self.inter_token = Histogram(buckets)
        self.handoff = Histogram(buckets)

    def _observe(self, hist, tag, ms):
        ms = max(float(ms), 0.0)
        hist.observe(ms)
        if self.monitor is not None:
            hook = getattr(self.monitor, "observe_histogram", None)
            if hook is not None:
                hook(tag, ms)

    def observe_admission_wait(self, seconds):
        self._observe(self.admission_wait, ADMISSION_WAIT, seconds * 1e3)

    def observe_ttft(self, seconds):
        self._observe(self.ttft, TTFT, seconds * 1e3)

    def observe_inter_token(self, seconds):
        self._observe(self.inter_token, INTER_TOKEN, seconds * 1e3)

    def observe_handoff(self, seconds):
        self._observe(self.handoff, HANDOFF, seconds * 1e3)

    def summary(self):
        """p50/p99 scalars (ms) for `serve_stats` — None-valued entries
        are omitted (no observations yet)."""
        out = {}
        for name, hist in (("admission_wait", self.admission_wait),
                           ("ttft", self.ttft),
                           ("inter_token", self.inter_token),
                           ("handoff", self.handoff)):
            for q, label in ((0.5, "p50"), (0.99, "p99")):
                value = hist.percentile(q)
                if value is not None:
                    out[f"{name}_{label}_ms"] = value
        return out
