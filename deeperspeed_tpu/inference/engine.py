"""Serving engine: continuous batching over a paged KV cache.

`InferenceEngine` is the serving-side sibling of the training
`DeepSpeedEngine`: it wraps the same model families (GPT-NeoX / GPT-2 —
their blocks share ONE implementation, `gpt_neox._block_qkv` /
`_block_post_attn`, so the decode path cannot drift from training
numerics), is driven by the same JSON config machinery (the validated
``"inference"`` block, `runtime.config.parse_inference_block`), loads
weights params-only through the manifest-verified checkpoint loader
(`checkpoint.load_module_checkpoint` — CRC verification and the
committed-tag fallback included, Adam moments never deserialized), and
applies `module_inject.prepare_inference_params` so weights rest in the
serving compute dtype.

Execution model (docs/inference.md):

- **Prefill/decode split.** New requests run one bucketed prefill
  (whole prompt, causal attention, K/V written to their pages in
  whole-page scatters); in-flight requests run one decode step each
  (one token through the Pallas paged decode-attention kernel,
  `ops/pallas/decode_attention.py`).
- **Fixed compiled shapes.** Prefill compiles per (batch bucket, length
  bucket), decode per batch bucket — the scheduler
  (`inference.scheduler`) only ever emits those shapes, so after the
  ladder warms up XLA never recompiles (`compile_count()` pins this in
  tests and the `DS_BENCH_SERVE` row).
- **State.** The page pools are donated through every compiled call and
  rebound, so XLA updates them in place; everything else (params,
  rotary cache) is read-only.

Sampling is deterministic: temperature 0 (default) is argmax;
temperature > 0 draws from `jax.random.categorical` under a fixed
config seed folded with the step counter — the same request stream
always produces the same tokens.
"""

import random
import time
import types
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..compat import shard_map
from ..models import gpt2 as gpt2_mod
from ..models import gpt_neox as neox
from ..module_inject.replace_module import prepare_inference_params
from ..ops.pallas.decode_attention import paged_decode_attention
from ..parallel.mesh import MODEL_AXIS
from ..runtime.config import (DeepSpeedConfig, parse_inference_block,
                              parse_quantization_block)
from ..runtime.config_utils import (DeepSpeedConfigError, load_config_json)
from ..runtime.fault_injection import (FaultInjector, InjectedServingFault,
                                       SERVING_FAULT_KINDS)
from ..runtime.precision import resolve_kv_cache_dtype
from ..utils.kv_retry import backoff_delay
from ..utils.logging import logger
from .admission import (AdmissionController, DrainAborted, RequestFailed,
                        validate_priority)
from .kv_cache import (PagedKVCache, QuantizedPages, pages_for_tokens,
                       quantize_kv)
from .metrics import REQUEST_STATUS_FAMILIES, ServeRequestMetrics
from .scheduler import (FINISHED, RUNNING, ContinuousBatchingScheduler,
                        Request)


def _pow2_ladder(lo, hi):
    """lo, 2·lo, 4·lo, ... capped at hi (hi appended if not reached)."""
    out, v = [], lo
    while v < hi:
        out.append(v)
        v *= 2
    out.append(hi)
    return sorted(set(out))


class _Family:
    """The model-family seams the serving loop needs: token embedding,
    position stream, LM head. Everything between (the block body) is
    the shared `gpt_neox._block_qkv`/`_block_post_attn`."""

    def __init__(self, model, max_seq_len):
        self.cfg = model.config
        if isinstance(model, neox.GPTNeoX):
            self.kind = "gpt_neox"
            self._cos, self._sin, self.rot_dim = neox._rotary_cache(
                self.cfg, max_seq_len)
        elif isinstance(model, gpt2_mod.GPT2):
            self.kind = "gpt2"
            self._cos = jnp.zeros((max_seq_len, 0), jnp.float32)
            self._sin = jnp.zeros((max_seq_len, 0), jnp.float32)
            self.rot_dim = 0
        else:
            raise DeepSpeedConfigError(
                f"InferenceEngine serves the GPT-NeoX / GPT-2 families; "
                f"got {type(model).__name__}")

    def embed_prefill(self, params, tokens):
        """tokens [B, S] → [B, S, H] at absolute positions 0..S-1."""
        x = params["embed"]["wte"][tokens]
        if self.kind == "gpt2":
            x = x + params["embed"]["wpe"][:tokens.shape[1]][None]
        return x

    def embed_decode(self, params, tokens, positions):
        """tokens [B] at absolute `positions` [B] → [B, 1, H]."""
        x = params["embed"]["wte"][tokens][:, None, :]
        if self.kind == "gpt2":
            x = x + params["embed"]["wpe"][positions][:, None, :]
        return x

    def cos_sin_prefill(self, seqlen):
        return (self._cos[:seqlen], self._sin[:seqlen], self.rot_dim)

    def cos_sin_decode(self, positions):
        """Per-batch rotary rows at `positions` [B] → ([B, 1, rot], ...)."""
        return (self._cos[positions][:, None, :],
                self._sin[positions][:, None, :], self.rot_dim)

    def head(self, params, h):
        """Final-norm hidden [B, H] → logits [B, V] (fp32)."""
        if self.kind == "gpt2":
            wte = params["embed"]["wte"]
        else:
            wte = params.get("embed_out", params["embed"])["wte"]
        return jnp.einsum("bh,vh->bv", h, wte.astype(h.dtype),
                          preferred_element_type=jnp.float32)


class InferenceEngine:
    """Continuous-batching serving over the paged KV cache.

    ``model`` is a `models.gpt_neox.GPTNeoX` or `models.gpt2.GPT2`
    wrapper; ``config`` a dict / JSON path / `DeepSpeedConfig` holding
    the validated ``"inference"`` block; ``params`` an optional natural
    parameter pytree (else `load_checkpoint` or `model.init_params`).
    """

    def __init__(self, model, config=None, config_params=None, params=None,
                 mesh=None, rng=None, monitor=None):
        self.model = model
        cfg = model.config
        if getattr(cfg, "moe_num_experts", 0):
            raise DeepSpeedConfigError(
                "serving MoE models is not supported yet: the decode "
                "block would silently drop the expert routing")
        if getattr(cfg, "attention_engine", "dense") != "dense":
            raise DeepSpeedConfigError(
                "serving needs attention_engine='dense' (the block-"
                "sparse engine has no decode variant)")
        if getattr(model, "_attn_fn", None) is not None:
            raise DeepSpeedConfigError(
                "serving a sequence-parallel model is not supported "
                "(decode is one token; there is no sequence to shard)")

        # -- config --------------------------------------------------------
        raw = config_params if config_params is not None else config
        if isinstance(raw, DeepSpeedConfig):
            self.inference_params = raw.inference_params
            telemetry_config = raw.telemetry_config
            quantization = raw.quantization_config
        else:
            if raw is None:
                raise DeepSpeedConfigError(
                    "InferenceEngine requires a config with an "
                    "'inference' block")
            d = raw if isinstance(raw, dict) else load_config_json(raw)
            self.inference_params = parse_inference_block(d)
            quantization = parse_quantization_block(d) or None
            # reuse the training parser's telemetry validation without
            # dragging in the batch triad it also wants
            ns = types.SimpleNamespace()
            DeepSpeedConfig._parse_telemetry_block(ns, d)
            telemetry_config = ns.telemetry_config
        if not self.inference_params:
            raise DeepSpeedConfigError(
                "the 'inference' config block is required (with "
                "\"enabled\": true) to build an InferenceEngine")
        ip = self.inference_params

        self.page_size = ip["page_size"]
        self.max_seq_len = ip["max_seq_len"] or cfg.max_seq_len
        if self.max_seq_len > cfg.max_seq_len:
            raise DeepSpeedConfigError(
                f"inference.max_seq_len {self.max_seq_len} exceeds the "
                f"model's max_seq_len {cfg.max_seq_len}")
        if self.max_seq_len % self.page_size:
            raise DeepSpeedConfigError(
                f"the serving window max_seq_len {self.max_seq_len} must "
                f"be a multiple of page_size {self.page_size} (the paged "
                f"re-prefill ladder cannot cover a misaligned tail); set "
                f"inference.max_seq_len explicitly")
        if ip["num_pages"] - 1 < pages_for_tokens(self.max_seq_len,
                                                  self.page_size):
            raise DeepSpeedConfigError(
                f"inference.num_pages {ip['num_pages']} cannot hold even "
                f"one max_seq_len sequence "
                f"({pages_for_tokens(self.max_seq_len, self.page_size)} "
                f"pages + the reserved trash page)")
        self.max_batch_size = ip["max_batch_size"]
        self.temperature = ip["temperature"]
        self.seed = ip["seed"]
        self._attn_backend = (None if ip["kernel"] == "auto"
                              else ip["kernel"])

        if ip["prefill_lengths"]:
            bad = [b for b in ip["prefill_lengths"] if b > self.max_seq_len]
            if bad:
                raise DeepSpeedConfigError(
                    f"inference.prefill_lengths {bad} exceed the serving "
                    f"window max_seq_len {self.max_seq_len}")
            self.prefill_lengths = ip["prefill_lengths"]
        else:
            self.prefill_lengths = _pow2_ladder(self.page_size,
                                                self.max_seq_len)
        self.prefill_batch_sizes = ip["prefill_batch_sizes"] or \
            [b for b in (1, 2, 4) if b <= self.max_batch_size]
        self.decode_batch_sizes = ip["decode_batch_sizes"] or \
            _pow2_ladder(1, self.max_batch_size)

        # -- mesh / params -------------------------------------------------
        self.mesh = mesh
        self.mp = 1
        if mesh is not None and MODEL_AXIS in mesh.axis_names:
            self.mp = int(mesh.shape[MODEL_AXIS])
        if params is None:
            params = model.init_params(
                rng if rng is not None else jax.random.PRNGKey(0))
        # compute dtype comes from a matmul WEIGHT: 1-D leaves (biases,
        # norms) deliberately rest in fp32 (`prepare_inference_params`),
        # so the first leaf would read fp32 off a bf16 model and
        # silently double weight HBM
        leaves = jax.tree_util.tree_leaves(params)
        self.compute_dtype = next(
            (leaf.dtype for leaf in leaves
             if getattr(leaf, "ndim", 0) >= 2), leaves[0].dtype)
        # kv_cache_dtype overrides the CACHE pools only (K/V are cast —
        # or int8-quantized with per-page scales — on write, attention
        # runs at pool dtype) — it never re-casts the weights
        kv_dtype = ip["kv_cache_dtype"]
        self.kv_cache_dtype = (resolve_kv_cache_dtype(kv_dtype)
                               if kv_dtype else self.compute_dtype)
        self.kv_quant = self.kv_cache_dtype == jnp.int8
        # the validated "quantization" block (weights choice): int8
        # block matmul weights at rest (docs/quantization.md)
        self.weight_quant = (quantization or {}).get("weights")
        if self.weight_quant and self.mp > 1:
            raise DeepSpeedConfigError(
                "quantization.weights with a model-parallel mesh is "
                "unsupported: the per-channel scale leaves have no "
                "tensor-parallel placement yet — serve quantized "
                "weights on a replicated (mp=1) mesh")
        # structure template for params-only checkpoint loads: the
        # QUANTIZED tree splits each weight into (qval, scale) leaves,
        # but checkpoints store the natural layout — keep an abstract
        # natural-structure template (shapes only, nothing resident)
        self._natural_like = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                           jnp.result_type(l)), params)
        params = prepare_inference_params(params, self.compute_dtype,
                                          weight_quant=self.weight_quant)
        self._set_params(params)

        # -- cache / scheduler ---------------------------------------------
        self.family = _Family(model, self.max_seq_len)
        self.cache = PagedKVCache(
            num_layers=cfg.num_layers, num_pages=ip["num_pages"],
            num_heads=cfg.num_heads, page_size=self.page_size,
            head_dim=cfg.head_dim, dtype=self.kv_cache_dtype, mesh=mesh)
        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_seq_len=self.max_seq_len,
            token_budget=ip["token_budget"],
            max_batch_size=self.max_batch_size,
            prefill_lengths=self.prefill_lengths,
            prefill_batch_sizes=self.prefill_batch_sizes,
            decode_batch_sizes=self.decode_batch_sizes)
        self.n_pages_max = pages_for_tokens(self.max_seq_len,
                                            self.page_size)
        # precision identity of this serving engine: the bench serve row
        # records it in `extra` so BENCH history can attribute serving
        # deltas to precision changes (docs/quantization.md)
        self.dtypes = {
            "weight": self.weight_quant or
            str(jnp.dtype(self.compute_dtype)),
            "compute": str(jnp.dtype(self.compute_dtype)),
            "kv_cache": ("int8" if self.kv_quant
                         else str(jnp.dtype(self.kv_cache_dtype))),
        }

        # -- telemetry (spans: schedule / prefill / decode; admission
        #    wait is a per-request scalar — docs/inference.md) ------------
        from ..runtime.telemetry import build_telemetry
        self.monitor = monitor
        self.telemetry = build_telemetry(telemetry_config, monitor=monitor,
                                         devices=jax.local_devices())

        self._compiled = {}
        self._steps = 0
        self.stats = {"steps": 0, "prefill_requests": 0,
                      "prefill_tokens": 0, "decode_tokens": 0,
                      "evictions": 0, "finished": 0,
                      "schedule_s": 0.0, "prefill_s": 0.0,
                      "decode_s": 0.0, "admission_wait_s": 0.0,
                      "queue_depth": 0.0, "page_pool_util": 0.0,
                      # terminal-status taxonomy: every request reaches
                      # exactly one (docs/inference.md)
                      "requests_ok": 0, "requests_shed": 0,
                      "requests_deadline_exceeded": 0,
                      "requests_failed": 0,
                      "quarantines": 0, "retries": 0}
        # request-level latency histograms (inference/metrics.py):
        # admission-wait / TTFT / inter-token distributions, fanned out
        # to the monitor's export backends (Prometheus histogram
        # families) at observation time
        self.request_metrics = ServeRequestMetrics(monitor=monitor)

        # graceful drain (SIGTERM): flag-only handler, acted on at the
        # next serving-loop iteration — the PR 3 signal discipline
        self.drain_deadline_s = ip["drain_deadline_s"]
        self._drain_requested = False
        self._drain_signum = None
        self._prev_handlers = {}

        # -- robustness layer (docs/inference.md "Serving under
        #    failure"): admission control, retry/poison policy, hang
        #    watchdog, serving fault injection -------------------------
        self.default_priority = ip["default_priority"]
        self.retry_params = ip["retry"]
        self._retry_rng = random.Random(ip["seed"])
        self.admission = (AdmissionController(ip["admission"])
                          if ip["admission"] else None)
        self.fault_injector = FaultInjector.from_config_env(
            config_spec=ip["fault_injection"])
        self._step_faults = []      # serving faults fired this step
        self._pressure_pages = []   # page_pool_pressure seizures
        self.watchdog = None
        self.watchdog_fires = 0
        self.last_stack_dump = None
        if ip["hang_timeout_s"] > 0:
            from ..runtime.sentinel import HangWatchdog
            self.watchdog = HangWatchdog(ip["hang_timeout_s"], self,
                                         "_on_serving_hang")

    # ------------------------------------------------------------------
    # weights
    # ------------------------------------------------------------------

    def _place_params(self, params):
        if self.mp > 1:
            specs = self.model.param_specs(params, self.mesh)
            return jax.tree_util.tree_map(
                lambda p, s: jax.device_put(
                    p, NamedSharding(self.mesh, s)), params, specs,
                is_leaf=lambda x: isinstance(x, P))
        return params

    def _set_params(self, params):
        """Place the params and pre-stack the block weights ONCE:
        decode is weight-bandwidth bound, and stacking inside the
        compiled step would materialize a full copy of the block
        params every call (params are runtime jit inputs — XLA cannot
        hoist the stack out)."""
        self.params = self._place_params(params)
        stacked = self._stacked_blocks(self.params)
        if self.mp > 1:
            specs = self.model.param_specs(self.params, self.mesh)
            stacked = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(self.mesh, P(None, *s))),
                stacked, specs["blocks"][0])
        self.params_stacked = stacked

    def load_checkpoint(self, load_dir, tag=None):
        """Params-only restore through the manifest-verified loader:
        CRC verification and the committed-tag fallback run exactly as
        in training resume, but only the module tree is deserialized —
        a serving restart never touches Adam moments."""
        from ..checkpoint.checkpointing import load_module_checkpoint
        path, natural, client_state = load_module_checkpoint(
            load_dir, tag=tag, like=self._natural_like)
        if path is None:
            return None, {}
        params = prepare_inference_params(natural, self.compute_dtype,
                                          weight_quant=self.weight_quant)
        # the compiled programs take params as runtime arguments, so the
        # warmed bucket executables stay valid across a weight hot-swap
        # (same avals = jit cache hit) — no recompile ladder to repay
        self._set_params(params)
        return path, client_state

    # ------------------------------------------------------------------
    # compiled programs (one per bucket — the no-recompile discipline)
    # ------------------------------------------------------------------

    def compile_count(self):
        """Total compiled executables across all bucketed programs; the
        zero-recompile tests/bench pin that this stops growing once the
        bucket ladder has warmed up."""
        total = 0
        for fn in self._compiled.values():
            total += (fn._cache_size() if hasattr(fn, "_cache_size")
                      else 1)
        return total

    def _sample(self, logits, rng):
        if self.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            rng, logits / self.temperature, axis=-1).astype(jnp.int32)

    def _attention(self, q, k_pages, v_pages, page_table, lengths):
        """Paged decode attention, shard_mapped over the model axis when
        the mesh shards heads (attention is head-independent, so each
        shard runs the kernel on its local heads — no collective).
        Int8 pools arrive as `QuantizedPages`; the per-page scale pools
        ride the same head-sharded placement as the data pools."""
        scales = {}
        if isinstance(k_pages, QuantizedPages):
            scales = {"k_scales": k_pages.scale, "v_scales": v_pages.scale}
            k_pages, v_pages = k_pages.data, v_pages.data
        if self.mp > 1:
            def mapped(q, k, v, pt, ln, *sc):
                kw = ({"k_scales": sc[0], "v_scales": sc[1]} if sc
                      else {})
                return paged_decode_attention(
                    q, k, v, pt, ln, backend=self._attn_backend, **kw)

            pool_spec = P(None, MODEL_AXIS, None, None)
            scale_specs = ((P(None, MODEL_AXIS, None),) * 2 if scales
                           else ())
            f = shard_map(
                mapped, self.mesh,
                in_specs=(P(None, MODEL_AXIS, None), pool_spec,
                          pool_spec, P(None, None), P(None)) + scale_specs,
                out_specs=P(None, MODEL_AXIS, None),
                check_vma=False)
            return f(q, k_pages, v_pages, page_table, lengths,
                     *scales.values())
        return paged_decode_attention(q, k_pages, v_pages, page_table,
                                      lengths, backend=self._attn_backend,
                                      **scales)

    @staticmethod
    def _stacked_blocks(params):
        return jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *params["blocks"])

    def _prefill_fn(self, batch, seqlen):
        key = ("prefill", batch, seqlen)
        if key in self._compiled:
            return self._compiled[key]
        cfg = self.model.config
        fam = self.family
        use_pallas = getattr(self.model, "use_pallas", True)
        ps = self.page_size
        n_pages_row = seqlen // ps
        cos_sin = fam.cos_sin_prefill(seqlen)

        def prefill(params, stacked, tokens, lengths, page_table, k_pool,
                    v_pool, rng):
            B, S = tokens.shape
            pos = jnp.arange(S, dtype=jnp.int32)[None, :]
            # 1 = real token, 0 = pad: the segmented attention kernels
            # (and the XLA fallback's segment mask) then give each row
            # causal attention over its own tokens only
            seg = (pos < lengths[:, None]).astype(jnp.int32)
            x = fam.embed_prefill(params, tokens)

            def body(carry, bp):
                y, kv = neox._block_core(
                    cfg, bp, carry, cos_sin, use_pallas, mp=1,
                    reduce_fn=lambda t: t, return_kv=True,
                    segment_ids=seg)
                return y, kv

            x, (ks, vs) = jax.lax.scan(body, x, stacked)

            # whole-page scatter: [B, S, H, D] → B·S/ps page tiles at
            # the page-table ids (pad rows hold table id 0 — the trash
            # page — so duplicates only ever collide there)
            flat_pt = page_table.reshape(-1)
            H, D = cfg.num_heads, cfg.head_dim

            def write(pool, new):
                tiles = new.reshape(B, n_pages_row, ps, H, D)
                tiles = jnp.moveaxis(tiles, 3, 2)
                tiles = tiles.reshape(B * n_pages_row, H, ps, D)
                if isinstance(pool, QuantizedPages):
                    # int8 pages: quantize each (head, slot) vector and
                    # scatter data + scale through the same page ids
                    q8, sc = quantize_kv(tiles)
                    return QuantizedPages(
                        pool.data.at[flat_pt].set(q8),
                        pool.scale.at[flat_pt].set(
                            sc.astype(pool.scale.dtype)))
                return pool.at[flat_pt].set(tiles.astype(pool.dtype))

            k_pool = jax.vmap(write)(k_pool, ks)
            v_pool = jax.vmap(write)(v_pool, vs)

            idx = jnp.clip(lengths - 1, 0, S - 1)
            h_last = x[jnp.arange(B), idx][:, None, :]
            h_last = neox.layer_norm(h_last, params["final_ln"]["scale"],
                                     params["final_ln"]["bias"],
                                     cfg.layernorm_eps)
            logits = fam.head(params, h_last[:, 0])
            return self._sample(logits, rng), k_pool, v_pool

        fn = jax.jit(prefill, donate_argnums=(5, 6))
        self._compiled[key] = fn
        return fn

    def _decode_fn(self, batch):
        key = ("decode", batch)
        if key in self._compiled:
            return self._compiled[key]
        cfg = self.model.config
        fam = self.family
        ps = self.page_size
        H, D = cfg.num_heads, cfg.head_dim

        def decode(params, stacked, tokens, lengths, page_table, k_pool,
                   v_pool, rng):
            B = tokens.shape[0]
            # lengths INCLUDE the token decoded this step; 0 marks an
            # inactive (padding) row whose page table is all trash
            pos = jnp.maximum(lengths - 1, 0)
            x = fam.embed_decode(params, tokens, pos)
            cos, sin, rot_dim = fam.cos_sin_decode(pos)
            page_idx = jnp.take_along_axis(
                page_table, (pos // ps)[:, None], axis=1)[:, 0]
            slot = pos % ps

            def store(pool, vec):
                """One decoded token's K or V row into its page slot;
                int8 pools quantize per (head) vector and land the
                scale in the page-aligned scale pool."""
                if isinstance(pool, QuantizedPages):
                    q8, sc = quantize_kv(vec)
                    return QuantizedPages(
                        pool.data.at[page_idx, :, slot].set(q8),
                        pool.scale.at[page_idx, :, slot].set(
                            sc.astype(pool.scale.dtype)))
                return pool.at[page_idx, :, slot].set(
                    vec.astype(pool.dtype))

            def body(carry, xs):
                bp, kp, vp = xs
                q, k, v = neox._block_qkv(cfg, bp, carry, cos, sin,
                                          rot_dim, H)
                kp = store(kp, k[:, 0])
                vp = store(vp, v[:, 0])
                qrow = q[:, 0] if isinstance(kp, QuantizedPages) \
                    else q[:, 0].astype(kp.dtype)
                attn = self._attention(qrow, kp, vp,
                                       page_table, lengths)
                attn = attn.astype(carry.dtype)
                out = neox._block_post_attn(
                    cfg, bp, carry, attn.reshape(B, 1, H * D),
                    reduce_fn=lambda t: t)
                return out, (kp, vp)

            x, (k_pool, v_pool) = jax.lax.scan(
                body, x, (stacked, k_pool, v_pool))
            h = neox.layer_norm(x, params["final_ln"]["scale"],
                                params["final_ln"]["bias"],
                                cfg.layernorm_eps)
            logits = fam.head(params, h[:, 0])
            return self._sample(logits, rng), k_pool, v_pool

        fn = jax.jit(decode, donate_argnums=(5, 6))
        self._compiled[key] = fn
        return fn

    # ------------------------------------------------------------------
    # the serving loop
    # ------------------------------------------------------------------

    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               request_id=None, priority=None, deadline_ms=None,
               ttft_slo_ms=None):
        """Enqueue one request; returns its id.

        ``priority`` is a class name (``interactive``/``batch``;
        config ``inference.default_priority`` when omitted) — typos
        raise with the choices listed. ``deadline_ms`` bounds the
        request's total wall clock (expired requests terminate with a
        typed `DeadlineExceeded`); ``ttft_slo_ms`` is its
        time-to-first-token objective (admission sheds the request when
        the measured TTFT EMA already exceeds it).

        Under overload the admission controller raises a typed
        `RequestRejected` (terminal status ``shed``) carrying a
        retry-after hint from the measured drain rate — the request
        never enters the queue."""
        priority = self.default_priority if priority is None else priority
        validate_priority(priority)
        for name, value in (("deadline_ms", deadline_ms),
                            ("ttft_slo_ms", ttft_slo_ms)):
            if value is not None and (
                    not isinstance(value, (int, float)) or
                    isinstance(value, bool) or value <= 0):
                raise ValueError(
                    f"{name} must be a number > 0 (milliseconds), got "
                    f"{value!r}")
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id, request_id=request_id,
                      priority=priority,
                      deadline_ms=(None if deadline_ms is None
                                   else float(deadline_ms)),
                      ttft_slo_ms=(None if ttft_slo_ms is None
                                   else float(ttft_slo_ms)))
        if self.admission is not None:
            usable = max(self.cache.num_pages - 1, 1)
            try:
                self.admission.admit(
                    req, queue_depth=len(self.scheduler.waiting) +
                    len(self.scheduler.quarantined),
                    page_pool_util=1.0 - self.cache.num_free / usable)
            except Exception:
                self.stats["requests_shed"] += 1
                raise
        return self.scheduler.add_request(req, now=time.perf_counter())

    def _next_rng(self):
        self._steps += 1
        return jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                  self._steps)

    def step(self):
        """One scheduler step: admit + prefill new requests, decode one
        token for every in-flight sequence. Returns a summary dict.

        A prefill/decode exception QUARANTINES the implicated batch
        (evict, free pages, capped-jittered retry; poisoned after
        ``retry.max_attempts`` consecutive failures) instead of killing
        the server — `step()` only raises on scheduler-invariant
        violations. The hang watchdog (``inference.hang_timeout_s``) is
        armed around the dispatch once the step's programs are warm
        (an XLA compile is not a hang) and fed on exit — including when
        the step DIES rather than hangs."""
        self._plan_step_faults()
        self._apply_page_pressure()
        try:
            return self._step_inner()
        finally:
            if self.watchdog is not None:
                self.watchdog.feed()
            self._release_page_pressure()

    def _step_inner(self):
        now = time.perf_counter()
        t0 = now
        finished_before = len(self.scheduler.finished)
        with self.telemetry.span("schedule"):
            plan = self.scheduler.schedule(now=now)
        self.stats["schedule_s"] += time.perf_counter() - t0
        self.stats["evictions"] += len(plan.evicted)
        if plan.empty and self.scheduler.quarantined:
            # nothing dispatchable until a quarantine backoff window
            # closes: sleep toward the earliest retry_at (capped so
            # run()/drain() stay responsive to drain requests and
            # deadlines) instead of busy-spinning step() at full CPU —
            # an uncapped spin would also flood the monitor and burn
            # scripted fault-injection step windows on idle serials
            wake = min((r.retry_at for r in self.scheduler.quarantined
                        if r.retry_at is not None), default=now)
            time.sleep(min(max(wake - time.perf_counter(), 0.0), 0.05))
        for req in plan.prefills:
            if req.admitted_at is not None and req.enqueued_at is not None:
                wait = req.admitted_at - req.enqueued_at
                self.stats["admission_wait_s"] += wait
                self.request_metrics.observe_admission_wait(wait)
        # per-step gauges: scheduler backlog + KV page-pool occupancy —
        # the two saturation signals an autoscaler watches (and the
        # admission controller sheds on)
        usable = max(self.cache.num_pages - 1, 1)
        self.stats["queue_depth"] = float(len(self.scheduler.waiting))
        self.stats["page_pool_util"] = 1.0 - self.cache.num_free / usable

        if self.watchdog is not None and self._programs_warm(plan):
            self.watchdog.arm()

        if plan.prefills:
            t0 = time.perf_counter()
            ok = True
            with self.telemetry.span("prefill"):
                try:
                    fault = self._fault_fired("prefill_error")
                    if fault is not None:
                        raise InjectedServingFault(
                            "injected prefill_error fault")
                    self._run_prefill(plan)
                except Exception as e:  # noqa: BLE001 - quarantine, don't die
                    ok = False
                    self._quarantine_batch(plan.prefills, e, "prefill")
            self.stats["prefill_s"] += time.perf_counter() - t0
            if ok:
                self.stats["prefill_requests"] += len(plan.prefills)
                # r.cached is the pre-sampling context length (complete_
                # prefill pins it before appending the first token) —
                # len(r.context) here would double-count that token once
                # decode accounting starts
                self.stats["prefill_tokens"] += \
                    sum(r.cached for r in plan.prefills)

        # a mid-execution prefill failure may have run cache-loss
        # recovery, evicting EVERY running sequence (their K/V is
        # gone): the planned decode batch would read trash pages and
        # append garbage tokens — skip it; the evicted requests
        # re-prefill on later steps
        decodes_intact = all(r.state == RUNNING for r in plan.decodes)
        if plan.decodes and decodes_intact:
            stall = self._fault_fired("decode_stall")
            if stall is not None:
                time.sleep(stall["seconds"])   # drives the watchdog
            t0 = time.perf_counter()
            ok = True
            with self.telemetry.span("decode"):
                try:
                    fault = self._fault_fired("decode_error")
                    if fault is not None:
                        raise InjectedServingFault(
                            "injected decode_error fault")
                    self._run_decode(plan)
                except Exception as e:  # noqa: BLE001
                    ok = False
                    self._quarantine_batch(plan.decodes, e, "decode")
            self.stats["decode_s"] += time.perf_counter() - t0
            if ok:
                self.stats["decode_tokens"] += len(plan.decodes)

        finished = len(self.scheduler.finished) - finished_before
        self.stats["finished"] += finished
        self.stats["steps"] += 1
        self._sync_status_counts()
        if self.admission is not None and finished:
            self.admission.note_finished(finished)
        self._record_request_spans(plan)
        if self.monitor is not None:
            # per-step saturation series keyed by total generated tokens
            # (the Serve/* convention); buffered — no per-step flush
            total = self.stats["prefill_tokens"] + \
                self.stats["decode_tokens"]
            scalars = {
                "Serve/queue_depth": self.stats["queue_depth"],
                "Serve/page_pool_util": self.stats["page_pool_util"],
                "Serve/running": float(len(self.scheduler.running))}
            # per-status terminal counters: exported through every
            # monitor backend (Prometheus gauges + JSONL events)
            for status, tag in REQUEST_STATUS_FAMILIES.items():
                scalars[tag] = float(self.stats[f"requests_{status}"])
            self.monitor.record(total, scalars)
        return {"prefilled": len(plan.prefills),
                "decoded": len(plan.decodes) if decodes_intact else 0,
                "evicted": len(plan.evicted), "finished": finished}

    def _sync_status_counts(self):
        """Mirror the scheduler's terminal-status tallies into the
        engine stats (``shed`` is engine-owned: shed requests never
        enter the scheduler)."""
        sc = self.scheduler.status_counts
        self.stats["requests_ok"] = sc["ok"]
        self.stats["requests_deadline_exceeded"] = sc["deadline_exceeded"]
        self.stats["requests_failed"] = sc["failed"]

    # ------------------------------------------------------------------
    # step-failure quarantine + serving fault injection
    # ------------------------------------------------------------------

    def _plan_step_faults(self):
        """One injector turn per serving step: pop the serving-kind
        host faults fired for this step (training kinds in a shared
        DS_FAULT_INJECT plan are ignored here)."""
        self._step_faults = []
        if self.fault_injector is None:
            return
        self.fault_injector.plan_next_step()
        self._step_faults = [
            f for f in self.fault_injector.take_host_faults()
            if f["kind"] in SERVING_FAULT_KINDS]

    def _fault_fired(self, kind):
        return next((f for f in self._step_faults if f["kind"] == kind),
                    None)

    def _apply_page_pressure(self):
        """``page_pool_pressure`` fault: seize a fraction of the FREE
        pool for this step so scheduling runs under memory pressure
        (eviction, admission shedding); released at step end."""
        fault = self._fault_fired("page_pool_pressure")
        if fault is None:
            return
        n = int(self.cache.num_free * fault["factor"])
        got = self.cache.allocate(n)
        if got:
            self._pressure_pages.extend(got)
            logger.warning(
                f"fault injection: page_pool_pressure seized {len(got)} "
                f"free page(s) for this step")

    def _release_page_pressure(self):
        if self._pressure_pages:
            self.cache.free(self._pressure_pages)
            self._pressure_pages = []

    def _quarantine_batch(self, requests, exc, phase):
        """A prefill/decode step failed: quarantine every implicated
        request (attribution is batch-granular — the failing request
        cannot be identified inside one compiled call; innocent
        co-batched requests reset their failure run at their next
        completed step). Transient failures get capped-jittered
        retries; a request failing ``retry.max_attempts`` consecutive
        steps is poisoned permanently with a typed `RequestFailed`
        (the serving mirror of PR 9's poison-step detector)."""
        now = time.perf_counter()
        self._recover_cache_if_lost(now)
        # the exception rides on poisoned requests (RequestFailed.
        # last_error) that live until the caller pops them: drop its
        # traceback NOW, or the stored frame graph pins this step's
        # plan/batch arrays (and the engine) for that whole lifetime
        exc.__traceback__ = None
        rp = self.retry_params
        poisoned = 0
        for req in requests:
            if req.state == FINISHED:
                continue       # cache-loss recovery may have failed it
            req.failures += 1
            if req.failures >= rp["max_attempts"]:
                poisoned += 1
                self.scheduler.finish_failed(req, RequestFailed(
                    f"request {req.request_id} failed {req.failures} "
                    f"consecutive {phase} steps — poisoned "
                    f"({type(exc).__name__}: {exc})",
                    last_error=exc, attempts=req.failures))
            else:
                delay_ms = backoff_delay(
                    req.failures, rp["backoff_base_ms"],
                    rp["backoff_cap_ms"], rp["jitter"], self._retry_rng)
                self.scheduler.quarantine_request(
                    req, retry_at=now + delay_ms / 1e3, now=now)
                self.stats["retries"] += 1
        self.stats["quarantines"] += 1
        logger.warning(
            f"serving {phase} step failed ({type(exc).__name__}: {exc}) "
            f"— quarantined {len(requests)} request(s) "
            f"({poisoned} poisoned); the server stays up")

    def _recover_cache_if_lost(self, now):
        """A compiled call that died MID-EXECUTION consumed the donated
        K/V pools: rebuild them zeroed and evict every running sequence
        (their cached context is gone — eviction re-prefills it from
        the full token history on readmission). Errors raised before
        dispatch (the common case, incl. injected faults) leave the
        donated buffers intact and skip this entirely."""
        k_data = self.cache.data_array(self.cache.k)
        deleted = getattr(k_data, "is_deleted", lambda: False)()
        if not deleted:
            return
        logger.error(
            "serving step died mid-execution with the KV pools donated "
            "— rebuilding zeroed pools and re-prefilling every running "
            "sequence")
        self.cache.reset_pools()
        while self.scheduler.running:
            self.scheduler._evict_victim(now)

    def _programs_warm(self, plan):
        """True when every compiled program this plan dispatches has
        at least one executable — the watchdog must not count a
        first-call XLA compile as a hang (the PR 4 discipline)."""
        def warm(key):
            fn = self._compiled.get(key)
            if fn is None:
                return False
            return (fn._cache_size() if hasattr(fn, "_cache_size")
                    else 1) >= 1
        if plan.empty:
            return False
        if plan.prefills and not warm(
                ("prefill", plan.prefill_batch, plan.prefill_len)):
            return False
        if plan.decodes and not warm(("decode", plan.decode_batch)):
            return False
        return True

    def _on_serving_hang(self):
        """Watchdog expiry (watchdog thread): the serving step blew its
        wall-clock deadline. Dump every thread's stack, then request a
        drain-style emergency flush — admissions stop NOW (flag write,
        async-signal-safe) and `run()` performs the full drain + typed
        in-flight failure + metrics flush if/when the stuck step
        returns."""
        from ..runtime.sentinel import dump_all_stacks
        self.watchdog_fires += 1
        self.last_stack_dump = dump_all_stacks()
        logger.error(
            f"serving hang watchdog: step exceeded "
            f"{self.watchdog.timeout_s:.1f}s — requesting an emergency "
            f"drain; all-thread stacks:\n{self.last_stack_dump}")
        self._drain_requested = True
        try:
            if self.monitor is not None:
                self.monitor.flush()
        except Exception:  # noqa: BLE001 - best-effort from the thread
            pass

    def _record_request_spans(self, plan):
        """Per-request lifecycle records behind the telemetry capture
        machinery: while a capture window is open, every request that
        FINISHED this step lands in the span buffer as one event
        covering submit → last token (exported in the Chrome trace next
        to the schedule/prefill/decode spans). Zero cost outside a
        window."""
        tracer = getattr(self.telemetry, "tracer", None)
        if tracer is None or not tracer.capturing:
            return
        now = time.perf_counter()
        for req in plan.prefills + plan.decodes:
            if req.state == FINISHED and req.submitted_at is not None:
                tracer.record_event(
                    f"request/{req.request_id}", req.submitted_at,
                    (req.last_token_at or now) - req.submitted_at)

    def _run_prefill(self, plan):
        B, S = plan.prefill_batch, plan.prefill_len
        n_pages_row = S // self.page_size
        tokens = np.zeros((B, S), np.int32)
        lengths = np.zeros((B,), np.int32)
        page_table = np.zeros((B, n_pages_row), np.int32)
        for i, req in enumerate(plan.prefills):
            ctx = req.context
            tokens[i, :len(ctx)] = ctx
            lengths[i] = len(ctx)
            page_table[i, :len(req.pages)] = req.pages
        fn = self._prefill_fn(B, S)
        nxt, self.cache.k, self.cache.v = fn(
            self.params, self.params_stacked, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(page_table), self.cache.k,
            self.cache.v, self._next_rng())
        nxt = np.asarray(nxt)
        now = time.perf_counter()
        for i, req in enumerate(plan.prefills):
            self.scheduler.complete_prefill(req, int(nxt[i]))
            # TTFT: once per request, from the ORIGINAL submit — an
            # evicted request's re-prefill resamples a token it already
            # delivered and must not re-count
            if req.first_token_at is None and req.submitted_at is not None:
                req.first_token_at = now
                ttft_s = now - req.submitted_at
                self.request_metrics.observe_ttft(ttft_s)
                if self.admission is not None:
                    # the shedding signal: measured TTFT EMA vs SLOs
                    self.admission.observe_ttft(ttft_s * 1e3)
            req.last_token_at = now

    def _run_decode(self, plan):
        B = plan.decode_batch
        tokens = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        page_table = np.zeros((B, self.n_pages_max), np.int32)
        for i, req in enumerate(plan.decodes):
            tokens[i] = req.generated[-1]
            lengths[i] = req.cached + 1
            page_table[i, :len(req.pages)] = req.pages
        fn = self._decode_fn(B)
        nxt, self.cache.k, self.cache.v = fn(
            self.params, self.params_stacked, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(page_table), self.cache.k,
            self.cache.v, self._next_rng())
        nxt = np.asarray(nxt)
        now = time.perf_counter()
        for i, req in enumerate(plan.decodes):
            self.scheduler.complete_decode(req, int(nxt[i]))
            if req.last_token_at is not None:
                self.request_metrics.observe_inter_token(
                    now - req.last_token_at)
            req.last_token_at = now

    # ------------------------------------------------------------------
    # graceful drain (SIGTERM from the pod scheduler)
    # ------------------------------------------------------------------
    #
    # Serving must NOT inherit the training engine's emergency-save
    # handler semantics: there is no state worth checkpointing mid-
    # decode, and dying mid-step wastes every in-flight sequence. The
    # right shutdown is: stop admitting, finish what's running (bounded
    # by `inference.drain_deadline_s`), flush the Serve/* telemetry,
    # exit 0 so the orchestrator sees a clean termination.

    def install_drain_handler(self):
        """Register SIGTERM/SIGINT to REQUEST a drain (flag only — the
        same async-signal-safe discipline as the training preemption
        handler); `run()` performs the actual drain at its next loop
        iteration. Weakly bound: the signal registry must not pin the
        engine (and its page pools) for the process lifetime."""
        import signal as _signal
        import threading
        import weakref
        if threading.current_thread() is not threading.main_thread():
            return self
        engine_ref = weakref.ref(self)

        def handler(signum, frame):  # noqa: ARG001
            engine = engine_ref()
            if engine is not None:
                engine._drain_requested = True
                engine._drain_signum = signum

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                self._prev_handlers[sig] = _signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
        return self

    def restore_signal_handlers(self):
        import signal as _signal
        for sig, handler in self._prev_handlers.items():
            try:
                _signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev_handlers = {}

    def request_drain(self):
        """Programmatic equivalent of the SIGTERM handler."""
        self._drain_requested = True

    def drain(self, deadline_s=None):
        """Stop admissions, finish in-flight sequences for at most
        `deadline_s` (config `inference.drain_deadline_s` by default),
        then flush Serve/* telemetry. Returns a summary dict; fresh
        queued requests are left unserved (`unserved` counts them) for
        the replacement instance.

        When the deadline elapses, still-in-flight requests are FAILED
        with a typed `DrainAborted` terminal status and flushed to the
        metrics before the process exits — previously they were
        silently abandoned, so a client could never distinguish a
        drain from a crash."""
        deadline_s = (self.drain_deadline_s if deadline_s is None
                      else float(deadline_s))
        self.scheduler.stop_admissions()
        t0 = time.perf_counter()
        deadline_hit = False
        while self.scheduler.has_inflight_work:
            if time.perf_counter() - t0 > deadline_s:
                deadline_hit = True
                break
            self.step()
        abandoned = 0
        for req in self.scheduler.inflight_requests():
            self.scheduler.finish_failed(req, DrainAborted(
                f"graceful-drain deadline ({deadline_s:.1f}s) elapsed "
                f"with request {req.request_id} still in flight "
                f"({len(req.generated)}/{req.max_new_tokens} tokens "
                f"generated)", attempts=req.failures))
            abandoned += 1
        self._sync_status_counts()
        summary = {
            "drained_s": time.perf_counter() - t0,
            "deadline_hit": deadline_hit,
            "inflight_abandoned": abandoned,
            "unserved": sum(1 for r in self.scheduler.waiting
                            if not r.evictions),
        }
        self.serve_stats()          # pushes Serve/* scalars (incl. the
        # per-status terminal counters — the DrainAborted failures land
        # in Serve/requests_failed BEFORE the monitor closes)
        if self.monitor is not None:
            self.monitor.close()    # drain the buffered scalar queue
        self.telemetry.close()
        self.restore_signal_handlers()
        logger.info(f"inference drain complete: {summary}")
        return summary

    def run(self, max_steps=None):
        """Drive steps until the queue drains (or `max_steps`). A
        pending drain request (SIGTERM via `install_drain_handler`, or
        `request_drain()`) switches to the graceful-drain path and exits
        the process with code 0 once in-flight work is finished — also
        on an IDLE server (nothing in flight ⇒ the drain is just the
        telemetry flush + exit; the SIGTERM contract must not depend on
        traffic being present)."""
        steps = 0
        while True:
            if self._drain_requested:
                self.drain()
                raise SystemExit(0)
            if not self.scheduler.has_work:
                break
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    def generate(self, prompts, max_new_tokens, eos_token_id=None):
        """Batch convenience: submit every prompt, drain the queue, and
        return the generated token lists in submission order. Consumes
        `scheduler.pop_finished()` (including any requests already
        finished by earlier manual `step()` driving), so the finished
        list cannot grow across repeated calls."""
        ids = [self.submit(p, max_new_tokens, eos_token_id=eos_token_id)
               for p in prompts]
        done = {}
        while self.scheduler.has_work:
            self.step()
            for r in self.scheduler.pop_finished():
                done[r.request_id] = r
        return [list(done[i].generated) for i in ids]

    def serve_stats(self):
        """Counters + phase seconds + request-latency percentiles
        (p50/p99 of admission wait / TTFT / inter-token, from the
        fixed-bucket histograms); also pushed to the monitor (as
        ``Serve/*`` scalars keyed by total generated tokens) when one
        was attached."""
        out = dict(self.stats)
        out.update(self.request_metrics.summary())
        total = out["prefill_tokens"] + out["decode_tokens"]
        if self.monitor is not None:
            self.monitor.record(
                total, {f"Serve/{k}": float(v) for k, v in out.items()})
        return out
